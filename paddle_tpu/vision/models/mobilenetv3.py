"""MobileNetV3 Small/Large (reference:
python/paddle/vision/models/mobilenetv3.py API)."""
from paddle_tpu import nn


def _make_divisible(v, divisor=8):
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _SE(nn.Layer):
    def __init__(self, ch, squeeze=4):
        super().__init__()
        mid = _make_divisible(ch // squeeze)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, mid, 1)
        self.fc2 = nn.Conv2D(mid, ch, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _InvertedResidual(nn.Layer):
    def __init__(self, in_ch, mid, out_ch, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_ch == out_ch
        layers = []
        Act = nn.Hardswish if act == "hardswish" else nn.ReLU
        if mid != in_ch:
            layers += [nn.Conv2D(in_ch, mid, 1, bias_attr=False),
                       nn.BatchNorm2D(mid), Act()]
        layers += [nn.Conv2D(mid, mid, k, stride=stride,
                             padding=k // 2, groups=mid, bias_attr=False),
                   nn.BatchNorm2D(mid), Act()]
        if use_se:
            layers.append(_SE(mid))
        layers += [nn.Conv2D(mid, out_ch, 1, bias_attr=False),
                   nn.BatchNorm2D(out_ch)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_LARGE = [
    # k, mid, out, se, act, stride
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_SMALL = [
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class MobileNetV3(nn.Layer):
    def __init__(self, config, last_ch, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        sc = lambda c: _make_divisible(c * scale)  # noqa: E731
        self.conv0 = nn.Sequential(
            nn.Conv2D(3, sc(16), 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(sc(16)), nn.Hardswish())
        blocks = []
        in_ch = sc(16)
        for k, mid, out, se, act, st in config:
            blocks.append(_InvertedResidual(in_ch, sc(mid), sc(out), k, st,
                                            se, act))
            in_ch = sc(out)
        self.blocks = nn.Sequential(*blocks)
        last_conv = sc(config[-1][1])
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_ch, last_conv, 1, bias_attr=False),
            nn.BatchNorm2D(last_conv), nn.Hardswish())
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_conv, last_ch), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_ch, num_classes))

    def forward(self, x):
        x = self.conv_last(self.blocks(self.conv0(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(nn.Flatten(1)(x))
        return x


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 1024, scale, num_classes, with_pool)


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 1280, scale, num_classes, with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)
