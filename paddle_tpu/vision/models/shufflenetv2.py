"""ShuffleNetV2 (reference: python/paddle/vision/models/shufflenetv2.py
API)."""
import paddle_tpu as paddle
from paddle_tpu import nn


def _channel_shuffle(x, groups):
    return paddle.nn.functional.channel_shuffle(x, groups)


class _InvertedResidual(nn.Layer):
    def __init__(self, in_ch, out_ch, stride, act="relu"):
        super().__init__()
        self.stride = stride
        Act = nn.Swish if act == "swish" else nn.ReLU
        branch = out_ch // 2
        if stride == 2:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_ch, in_ch, 3, stride=2, padding=1,
                          groups=in_ch, bias_attr=False),
                nn.BatchNorm2D(in_ch),
                nn.Conv2D(in_ch, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), Act())
            b2_in = in_ch
        else:
            self.branch1 = None
            b2_in = in_ch // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), Act(),
            nn.Conv2D(branch, branch, 3, stride=stride, padding=1,
                      groups=branch, bias_attr=False),
            nn.BatchNorm2D(branch),
            nn.Conv2D(branch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), Act())

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1, x2 = x[:, :c], x[:, c:]
            out = paddle.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = paddle.concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


_CFGS = {
    "x0_25": ([24, 48, 96, 192], 512),
    "x0_33": ([24, 32, 64, 128], 512),
    "x0_5": ([24, 48, 96, 192], 1024),
    "x1_0": ([24, 116, 232, 464], 1024),
    "x1_5": ([24, 176, 352, 704], 1024),
    "x2_0": ([24, 244, 488, 976], 2048),
}
_REPEATS = [4, 8, 4]


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        key = {0.25: "x0_25", 0.33: "x0_33", 0.5: "x0_5", 1.0: "x1_0",
               1.5: "x1_5", 2.0: "x2_0"}[scale]
        chans, last = _CFGS[key]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, chans[0], 3, stride=2, padding=1,
                      bias_attr=False),
            nn.BatchNorm2D(chans[0]), nn.ReLU())
        self.maxpool = nn.MaxPool2D(3, 2, padding=1)
        blocks = []
        in_ch = chans[0]
        for stage, rep in enumerate(_REPEATS):
            out_ch = chans[stage + 1]
            blocks.append(_InvertedResidual(in_ch, out_ch, 2, act))
            for _ in range(rep - 1):
                blocks.append(_InvertedResidual(out_ch, out_ch, 1, act))
            in_ch = out_ch
        self.blocks = nn.Sequential(*blocks)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_ch, last, 1, bias_attr=False),
            nn.BatchNorm2D(last), nn.ReLU())
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(last, num_classes)

    def forward(self, x):
        x = self.conv_last(self.blocks(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(nn.Flatten(1)(x))
        return x


def _factory(scale, act="relu"):
    def f(pretrained=False, **kwargs):
        return ShuffleNetV2(scale=scale, act=act, **kwargs)
    return f


shufflenet_v2_x0_25 = _factory(0.25)
shufflenet_v2_x0_33 = _factory(0.33)
shufflenet_v2_x0_5 = _factory(0.5)
shufflenet_v2_x1_0 = _factory(1.0)
shufflenet_v2_x1_5 = _factory(1.5)
shufflenet_v2_x2_0 = _factory(2.0)
shufflenet_v2_swish = _factory(1.0, act="swish")
