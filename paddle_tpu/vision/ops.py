"""paddle.vision.ops — detection/vision operators (reference:
python/paddle/vision/ops.py over phi detection kernels).

TPU-native notes: box ops are pure jnp math (XLA fuses them); roi_align /
roi_pool are gather+interpolate over static grids; nms variants run the
data-dependent suppression loop as lax.fori over a fixed box budget so the
whole op stays jittable (the CUDA originals use dynamic work queues)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.dispatch import run_op
from paddle_tpu.core.tensor import Parameter, Tensor


def _t(x):
    import paddle_tpu as paddle
    return x if isinstance(x, Tensor) else paddle.to_tensor(x)


# ---------------------------------------------------------------------------
# boxes
# ---------------------------------------------------------------------------

def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (reference box_coder op)."""
    def f(pb, pbv, tb):
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        px = pb[:, 0] + pw * 0.5
        py = pb[:, 1] + ph * 0.5
        if code_type == "encode_center_size":
            tw = tb[:, None, 2] - tb[:, None, 0] + norm
            th = tb[:, None, 3] - tb[:, None, 1] + norm
            tx = tb[:, None, 0] + tw * 0.5
            ty = tb[:, None, 1] + th * 0.5
            ox = (tx - px[None]) / pw[None]
            oy = (ty - py[None]) / ph[None]
            ow = jnp.log(jnp.abs(tw / pw[None]))
            oh = jnp.log(jnp.abs(th / ph[None]))
            out = jnp.stack([ox, oy, ow, oh], -1)
            if pbv is not None:
                out = out / pbv[None]
            return out
        # decode_center_size
        if pbv is not None:
            tb = tb * (pbv[None] if pbv.ndim == 2 else pbv)
        if axis == 0:
            px_, py_, pw_, ph_ = (px[None, :], py[None, :],
                                  pw[None, :], ph[None, :])
        else:
            px_, py_, pw_, ph_ = (px[:, None], py[:, None],
                                  pw[:, None], ph[:, None])
        ox = tb[..., 0] * pw_ + px_
        oy = tb[..., 1] * ph_ + py_
        ow = jnp.exp(tb[..., 2]) * pw_
        oh = jnp.exp(tb[..., 3]) * ph_
        return jnp.stack([ox - ow * 0.5, oy - oh * 0.5,
                          ox + ow * 0.5 - norm,
                          oy + oh * 0.5 - norm], -1)
    pbv = _t(prior_box_var) if isinstance(prior_box_var, (Tensor, np.ndarray,
                                                          list)) else None
    args = [_t(prior_box)] + ([pbv] if pbv is not None else
                              [Tensor._wrap(jnp.ones((1, 4)))]) \
        + [_t(target_box)]
    if pbv is None:
        def g(pb, _unused, tb):
            return f(pb, None, tb)
        return run_op("box_coder", g, *args)
    return run_op("box_coder", f, *args)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior boxes over the feature map grid (reference prior_box)."""
    feat = _t(input)
    img = _t(image)
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    ars = list(aspect_ratios)
    if flip:
        ars = ars + [1.0 / a for a in aspect_ratios if a != 1.0]
    boxes = []
    for s in min_sizes:
        boxes.append((s, s))
        if max_sizes:
            for ms in max_sizes:
                boxes.append((np.sqrt(s * ms), np.sqrt(s * ms)))
        for a in ars:
            if abs(a - 1.0) < 1e-6:
                continue
            boxes.append((s * np.sqrt(a), s / np.sqrt(a)))
    num_priors = len(boxes)

    def f(_feat, _img):
        cx = (jnp.arange(fw) + offset) * step_w
        cy = (jnp.arange(fh) + offset) * step_h
        cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")
        out = []
        for bw, bh in boxes:
            out.append(jnp.stack([(cxg - bw / 2) / iw, (cyg - bh / 2) / ih,
                                  (cxg + bw / 2) / iw, (cyg + bh / 2) / ih],
                                 -1))
        b = jnp.stack(out, 2)          # [H, W, P, 4]
        if clip:
            b = jnp.clip(b, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, b.dtype),
                               b.shape)
        return b, var
    return run_op("prior_box", f, feat, img, n_outputs=2,
                  differentiable=False)


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Decode YOLOv3 head output to boxes+scores (reference yolo_box)."""
    na = len(anchors) // 2
    anc = np.asarray(anchors, np.float32).reshape(na, 2)

    def f(xa, imgs):
        n, c, h, w = xa.shape
        xa = xa.reshape(n, na, -1, h, w)
        grid_x = jnp.arange(w, dtype=xa.dtype)
        grid_y = jnp.arange(h, dtype=xa.dtype)
        gx, gy = jnp.meshgrid(grid_x, grid_y, indexing="xy")
        bx = (jax.nn.sigmoid(xa[:, :, 0]) * scale_x_y
              - (scale_x_y - 1) / 2 + gx) / w
        by = (jax.nn.sigmoid(xa[:, :, 1]) * scale_x_y
              - (scale_x_y - 1) / 2 + gy) / h
        in_w = downsample_ratio * w
        in_h = downsample_ratio * h
        bw = jnp.exp(xa[:, :, 2]) * anc[None, :, 0, None, None] / in_w
        bh = jnp.exp(xa[:, :, 3]) * anc[None, :, 1, None, None] / in_h
        obj = jax.nn.sigmoid(xa[:, :, 4])
        cls = jax.nn.sigmoid(xa[:, :, 5:5 + class_num])
        score = obj[:, :, None] * cls
        ih = imgs[:, 0].astype(xa.dtype)
        iw = imgs[:, 1].astype(xa.dtype)
        x0 = (bx - bw / 2) * iw[:, None, None, None]
        y0 = (by - bh / 2) * ih[:, None, None, None]
        x1 = (bx + bw / 2) * iw[:, None, None, None]
        y1 = (by + bh / 2) * ih[:, None, None, None]
        if clip_bbox:
            x0 = jnp.clip(x0, 0)
            y0 = jnp.clip(y0, 0)
            x1 = jnp.minimum(x1, iw[:, None, None, None] - 1)
            y1 = jnp.minimum(y1, ih[:, None, None, None] - 1)
        boxes = jnp.stack([x0, y0, x1, y1], -1).reshape(n, -1, 4)
        mask = obj.reshape(n, -1) > conf_thresh
        boxes = jnp.where(mask[..., None], boxes, 0.0)
        scores = score.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
        scores = jnp.where(mask[..., None], scores, 0.0)
        return boxes, scores
    return run_op("yolo_box", f, _t(x), _t(img_size), n_outputs=2,
                  differentiable=False)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0, name=None):
    """YOLOv3 training loss (reference yolo_loss kernel). Simplified
    dense-assignment variant: each gt is matched to its best anchor on its
    grid cell; objectness BCE everywhere else with ignore region."""
    na = len(anchor_mask)
    anc = np.asarray(anchors, np.float32).reshape(-1, 2)
    anc_m = anc[np.asarray(anchor_mask)]

    def f(xa, gb, gl):
        n, c, h, w = xa.shape
        xa = xa.reshape(n, na, 5 + class_num, h, w)
        in_w = downsample_ratio * w
        tx = jax.nn.sigmoid(xa[:, :, 0])
        ty = jax.nn.sigmoid(xa[:, :, 1])
        obj = xa[:, :, 4]
        # build targets densely
        gx = gb[..., 0] * w
        gy = gb[..., 1] * h
        gw = gb[..., 2]
        gh = gb[..., 3]
        valid = (gw > 0) & (gh > 0)
        # anchor match by IoU of (w,h)
        aw = anc_m[:, 0] / in_w
        ah = anc_m[:, 1] / in_w
        inter = jnp.minimum(gw[..., None], aw) * \
            jnp.minimum(gh[..., None], ah)
        union = gw[..., None] * gh[..., None] + aw * ah - inter
        best = jnp.argmax(inter / (union + 1e-9), -1)   # [N, B]
        ci = jnp.clip(gx.astype(jnp.int32), 0, w - 1)
        cj = jnp.clip(gy.astype(jnp.int32), 0, h - 1)
        # objectness target map
        tobj = jnp.zeros((n, na, h, w))
        bidx = jnp.arange(n)[:, None]
        tobj = tobj.at[bidx, best, cj, ci].max(valid.astype(tobj.dtype))
        obj_loss = jnp.maximum(obj, 0) - obj * tobj + \
            jnp.log1p(jnp.exp(-jnp.abs(obj)))
        # coordinate loss at assigned cells
        px = tx[bidx, best, cj, ci]
        py = ty[bidx, best, cj, ci]
        lx = (px - (gx - jnp.floor(gx))) ** 2
        ly = (py - (gy - jnp.floor(gy))) ** 2
        coord = jnp.sum((lx + ly) * valid, -1)
        cls_logits = xa[:, :, 5:]
        tcls = jax.nn.one_hot(gl, class_num)
        pc = cls_logits[bidx, best, :, cj, ci]
        cls_loss = jnp.sum(jnp.sum(
            (jnp.maximum(pc, 0) - pc * tcls
             + jnp.log1p(jnp.exp(-jnp.abs(pc)))), -1) * valid, -1)
        return jnp.sum(obj_loss, (1, 2, 3)) + coord + cls_loss
    return run_op("yolo_loss", f, _t(x), _t(gt_box), _t(gt_label))


# ---------------------------------------------------------------------------
# RoI ops
# ---------------------------------------------------------------------------

def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference roi_align kernel): bilinear sampling over a
    static grid per output cell — a gather, XLA-friendly."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    sr = sampling_ratio if sampling_ratio > 0 else 2

    def f(feat, bxs, bn):
        n, c, h, w = feat.shape
        nb = bxs.shape[0]
        # map each box to its batch image
        img_idx = jnp.repeat(jnp.arange(bn.shape[0]), nb // bn.shape[0]) \
            if False else jnp.cumsum(
            jnp.zeros(nb, jnp.int32).at[jnp.cumsum(bn)[:-1]].add(1))
        off = 0.5 if aligned else 0.0
        x0 = bxs[:, 0] * spatial_scale - off
        y0 = bxs[:, 1] * spatial_scale - off
        x1 = bxs[:, 2] * spatial_scale - off
        y1 = bxs[:, 3] * spatial_scale - off
        bw = x1 - x0
        bh = y1 - y0
        if not aligned:
            bw = jnp.maximum(bw, 1.0)
            bh = jnp.maximum(bh, 1.0)
        # sample grid [nb, oh*sr, ow*sr]
        gy = y0[:, None] + (jnp.arange(oh * sr) + 0.5)[None] * \
            (bh[:, None] / (oh * sr))
        gx = x0[:, None] + (jnp.arange(ow * sr) + 0.5)[None] * \
            (bw[:, None] / (ow * sr))

        def bilinear(iy, ix):
            yy0 = jnp.clip(jnp.floor(iy), 0, h - 1)
            xx0 = jnp.clip(jnp.floor(ix), 0, w - 1)
            yy1 = jnp.clip(yy0 + 1, 0, h - 1)
            xx1 = jnp.clip(xx0 + 1, 0, w - 1)
            ly = iy - yy0
            lx = ix - xx0
            ly = jnp.clip(ly, 0, 1)
            lx = jnp.clip(lx, 0, 1)

            def gather(yy, xx):
                # feat[img, :, yy, xx] for per-box yy [nb,H'] xx [nb,W']
                fy = feat[img_idx]          # [nb, c, h, w]
                out = fy[jnp.arange(nb)[:, None, None], :,
                         yy[:, :, None].astype(jnp.int32),
                         xx[:, None, :].astype(jnp.int32)]
                return out                  # [nb, H', W', c]
            v = (gather(yy0, xx0) * ((1 - ly)[:, :, None, None]
                                     * (1 - lx)[:, None, :, None])
                 + gather(yy1, xx0) * (ly[:, :, None, None]
                                       * (1 - lx)[:, None, :, None])
                 + gather(yy0, xx1) * ((1 - ly)[:, :, None, None]
                                       * lx[:, None, :, None])
                 + gather(yy1, xx1) * (ly[:, :, None, None]
                                       * lx[:, None, :, None]))
            return v                        # [nb, H', W', c]
        samples = bilinear(gy, gx)          # [nb, oh*sr, ow*sr, c]
        samples = samples.reshape(nb, oh, sr, ow, sr, -1)
        out = samples.mean((2, 4))          # [nb, oh, ow, c]
        return jnp.moveaxis(out, -1, 1)
    return run_op("roi_align", f, _t(x), _t(boxes), _t(boxes_num))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """RoIPool (reference roi_pool): adaptive max pool per box, computed
    via a dense sample grid (8 samples/cell) + max."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    sr = 4

    def f(feat, bxs, bn):
        n, c, h, w = feat.shape
        nb = bxs.shape[0]
        img_idx = jnp.cumsum(
            jnp.zeros(nb, jnp.int32).at[jnp.cumsum(bn)[:-1]].add(1))
        x0 = jnp.round(bxs[:, 0] * spatial_scale)
        y0 = jnp.round(bxs[:, 1] * spatial_scale)
        x1 = jnp.round(bxs[:, 2] * spatial_scale)
        y1 = jnp.round(bxs[:, 3] * spatial_scale)
        bw = jnp.maximum(x1 - x0 + 1, 1.0)
        bh = jnp.maximum(y1 - y0 + 1, 1.0)
        gy = y0[:, None] + (jnp.arange(oh * sr) + 0.5)[None] * \
            (bh[:, None] / (oh * sr))
        gx = x0[:, None] + (jnp.arange(ow * sr) + 0.5)[None] * \
            (bw[:, None] / (ow * sr))
        yy = jnp.clip(gy, 0, h - 1).astype(jnp.int32)
        xx = jnp.clip(gx, 0, w - 1).astype(jnp.int32)
        fy = feat[img_idx]
        out = fy[jnp.arange(nb)[:, None, None], :,
                 yy[:, :, None], xx[:, None, :]]   # [nb, H', W', c]
        out = out.reshape(nb, oh, sr, ow, sr, -1).max((2, 4))
        return jnp.moveaxis(out, -1, 1)
    return run_op("roi_pool", f, _t(x), _t(boxes), _t(boxes_num))


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pool (reference psroi_pool): channel k of
    output cell (i,j) pools from input channel group (i*ow+j)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size

    def f(feat, bxs, bn):
        n, c, h, w = feat.shape
        co = c // (oh * ow)
        nb = bxs.shape[0]
        img_idx = jnp.cumsum(
            jnp.zeros(nb, jnp.int32).at[jnp.cumsum(bn)[:-1]].add(1))
        # average pool each cell from its group channels
        x0 = bxs[:, 0] * spatial_scale
        y0 = bxs[:, 1] * spatial_scale
        bw = jnp.maximum((bxs[:, 2] - bxs[:, 0]) * spatial_scale, 0.1)
        bh = jnp.maximum((bxs[:, 3] - bxs[:, 1]) * spatial_scale, 0.1)
        sr = 4
        gy = y0[:, None] + (jnp.arange(oh * sr) + 0.5)[None] * \
            (bh[:, None] / (oh * sr))
        gx = x0[:, None] + (jnp.arange(ow * sr) + 0.5)[None] * \
            (bw[:, None] / (ow * sr))
        yy = jnp.clip(gy, 0, h - 1).astype(jnp.int32)
        xx = jnp.clip(gx, 0, w - 1).astype(jnp.int32)
        fy = feat[img_idx]                  # [nb, c, h, w]
        out = fy[jnp.arange(nb)[:, None, None], :,
                 yy[:, :, None], xx[:, None, :]]   # [nb, H', W', c]
        out = out.reshape(nb, oh, sr, ow, sr, c).mean((2, 4))
        # [nb, oh, ow, c] -> pick group channels
        out = out.reshape(nb, oh, ow, oh * ow, co)
        cell = (jnp.arange(oh)[:, None] * ow
                + jnp.arange(ow)[None, :])  # [oh, ow]
        picked = jnp.take_along_axis(
            out, cell[None, :, :, None, None], 3)[..., 0, :]
        return jnp.moveaxis(picked, -1, 1)
    return run_op("psroi_pool", f, _t(x), _t(boxes), _t(boxes_num))


# ---------------------------------------------------------------------------
# NMS family
# ---------------------------------------------------------------------------

def _iou_matrix(boxes):
    x0, y0, x1, y1 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(x1 - x0, 0) * jnp.maximum(y1 - y0, 0)
    ix0 = jnp.maximum(x0[:, None], x0[None, :])
    iy0 = jnp.maximum(y0[:, None], y0[None, :])
    ix1 = jnp.minimum(x1[:, None], x1[None, :])
    iy1 = jnp.minimum(y1[:, None], y1[None, :])
    inter = jnp.maximum(ix1 - ix0, 0) * jnp.maximum(iy1 - iy0, 0)
    return inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-9)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Hard NMS (reference nms op). Greedy suppression as a fori_loop over
    the score-ordered box list — static shapes, jittable."""
    b = _t(boxes)
    n = b.shape[0]

    def f(bx, *rest):
        sc = rest[0] if rest else jnp.arange(n, 0, -1).astype(bx.dtype)
        order = jnp.argsort(-sc)
        bs = bx[order]
        iou = _iou_matrix(bs)
        if categories is not None and rest[1:]:
            cat = rest[1][order]
            iou = jnp.where(cat[:, None] == cat[None, :], iou, 0.0)

        def body(i, keep):
            # suppress if overlaps any earlier kept box
            over = (iou[i] > iou_threshold) & (jnp.arange(n) < i) & keep
            return keep.at[i].set(~jnp.any(over))
        keep = lax.fori_loop(1, n, body, jnp.ones(n, bool))
        # kept boxes first (score order), suppressed after — the host
        # slices the first `count` entries for the dynamic-length result
        rank = jnp.where(keep, jnp.arange(n), n + jnp.arange(n))
        perm = jnp.argsort(rank)
        return order[perm], keep.sum()
    args = [b] + ([_t(scores)] if scores is not None else []) \
        + ([_t(category_idxs)] if category_idxs is not None else [])
    idx, count = run_op("nms", f, *args, n_outputs=2,
                        differentiable=False)
    k = int(count.numpy())
    out = idx[:k]
    if top_k is not None:
        out = out[:min(top_k, k)]
    return out


def matrix_nms(bboxes, scores, score_threshold, post_threshold,
               nms_top_k, keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2) — decay-based soft suppression; fully parallel,
    the idiomatic TPU NMS (reference matrix_nms op)."""
    def f(bx, sc):
        n, cls, _ = bx.shape if bx.ndim == 3 else (1,) + bx.shape
        bb = bx if bx.ndim == 3 else bx[None]
        ss = sc if sc.ndim == 3 else sc[None]
        outs = []
        for b_i in range(bb.shape[0]):
            per_cls = []
            for c_i in range(ss.shape[1]):
                if c_i == background_label:
                    continue
                s = ss[b_i, c_i]
                boxes_c = bb[b_i]
                order = jnp.argsort(-s)[:nms_top_k]
                s_o = s[order]
                b_o = boxes_c[order]
                iou = _iou_matrix(b_o)
                upper = jnp.triu(iou, 1)
                # decay per box: prod over higher-scored boxes
                max_iou = jnp.max(upper, 0)
                if use_gaussian:
                    decay = jnp.exp(-(upper ** 2 - max_iou[None] ** 2)
                                    / gaussian_sigma)
                    decay = jnp.min(jnp.where(upper > 0, decay, 1.0), 0)
                else:
                    decay = jnp.min(jnp.where(
                        upper > 0,
                        (1 - upper) / jnp.maximum(1 - max_iou[None], 1e-9),
                        1.0), 0)
                s_new = s_o * decay
                keep = s_new > post_threshold
                cls_col = jnp.full_like(s_new, c_i)
                entry = jnp.concatenate(
                    [cls_col[:, None], s_new[:, None], b_o], -1)
                entry = jnp.where(keep[:, None], entry, -1.0)
                per_cls.append(entry)
            cat = jnp.concatenate(per_cls, 0)
            order = jnp.argsort(-cat[:, 1])[:keep_top_k]
            outs.append(cat[order])
        return jnp.concatenate(outs, 0)
    out = run_op("matrix_nms", f, _t(bboxes), _t(scores),
                 differentiable=False)
    arr = out.numpy()
    valid = arr[:, 1] > 0
    import paddle_tpu as paddle
    kept = paddle.to_tensor(arr[valid])
    rois_num = paddle.to_tensor(np.asarray([int(valid.sum())], np.int32))
    if return_index:
        idx = paddle.to_tensor(np.nonzero(valid)[0].astype(np.int32))
        return (kept, idx, rois_num) if return_rois_num else (kept, idx)
    return (kept, rois_num) if return_rois_num else kept


# ---------------------------------------------------------------------------
# deformable conv
# ---------------------------------------------------------------------------

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference deform_conv2d): bilinear-sample
    the input at offset positions per kernel tap, then a dense matmul —
    gather + GEMM on the MXU instead of the CUDA scatter kernel."""
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)

    def f(xa, off, w, *rest):
        n, cin, h, wdt = xa.shape
        cout, cin_g, kh, kw = w.shape
        oh = (h + 2 * pd[0] - dl[0] * (kh - 1) - 1) // st[0] + 1
        ow = (wdt + 2 * pd[1] - dl[1] * (kw - 1) - 1) // st[1] + 1
        # base sampling positions per tap
        ys = jnp.arange(oh) * st[0] - pd[0]
        xs = jnp.arange(ow) * st[1] - pd[1]
        ky = jnp.arange(kh) * dl[0]
        kx = jnp.arange(kw) * dl[1]
        base_y = ys[:, None, None, None] + ky[None, None, :, None]
        base_x = xs[None, :, None, None] + kx[None, None, None, :]
        # offsets [N, 2*dg*kh*kw, oh, ow] -> [N, dg, kh, kw, 2, oh, ow]
        off = off.reshape(n, deformable_groups, kh * kw, 2, oh, ow)
        oy = off[:, :, :, 0].reshape(n, deformable_groups, kh, kw, oh, ow)
        ox = off[:, :, :, 1].reshape(n, deformable_groups, kh, kw, oh, ow)
        # full sampling coordinate per (tap, out position):
        # base [oh, ow, kh, kw] -> [1, 1, kh, kw, oh, ow]
        py = base_y.transpose(2, 3, 0, 1)[None, None] + oy
        px = base_x.transpose(2, 3, 0, 1)[None, None] + ox

        def sample(iy, ix):
            y0 = jnp.floor(iy)
            x0 = jnp.floor(ix)
            wy = iy - y0
            wx = ix - x0
            out = 0
            for (yy, ww_y) in ((y0, 1 - wy), (y0 + 1, wy)):
                for (xx, ww_x) in ((x0, 1 - wx), (x0 + 1, wx)):
                    valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < wdt)
                    yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
                    xc = jnp.clip(xx, 0, wdt - 1).astype(jnp.int32)
                    # gather per dg group; broadcast channels within group
                    # xa: [n, cin, h, w]; yc/xc: [n, dg, kh, kw, oh, ow]
                    cg = cin // deformable_groups
                    xg = xa.reshape(n, deformable_groups, cg, h, wdt)
                    g = xg[jnp.arange(n)[:, None, None, None, None, None],
                           jnp.arange(deformable_groups)[None, :, None,
                                                         None, None, None],
                           :, yc, xc]
                    # g: [n, dg, kh, kw, oh, ow, cg]
                    wgt = (ww_y * ww_x * valid)[..., None]
                    out = out + g * wgt
            return out                      # [n, dg, kh, kw, oh, ow, cg]
        cols = sample(py, px)
        if rest:  # modulation mask (v2)
            m = rest[0].reshape(n, deformable_groups, kh, kw, oh, ow)
            cols = cols * m[..., None]
        # [n, dg, kh, kw, oh, ow, cg] -> [n, cin*kh*kw, oh*ow]
        cols = cols.transpose(0, 1, 6, 2, 3, 4, 5).reshape(
            n, cin, kh, kw, oh, ow)
        cols2 = cols.reshape(n, cin * kh * kw, oh * ow)
        wmat = w.reshape(cout, cin_g * kh * kw)
        if groups == 1:
            out = jnp.einsum("ok,nkp->nop", wmat, cols2)
        else:
            cols_g = cols2.reshape(n, groups, (cin // groups) * kh * kw, -1)
            wg = wmat.reshape(groups, cout // groups, -1)
            out = jnp.einsum("gok,ngkp->ngop", wg, cols_g).reshape(
                n, cout, -1)
        out = out.reshape(n, cout, oh, ow)
        if len(rest) > 1:
            out = out + rest[1].reshape(1, -1, 1, 1)
        return out
    args = [_t(x), _t(offset), _t(weight)]
    if mask is not None:
        args.append(_t(mask))
    if bias is not None:
        if mask is None:
            # keep positional layout: mask slot then bias
            args.append(Tensor._wrap(jnp.ones(
                (int(_t(x).shape[0]), deformable_groups
                 * int(_t(weight).shape[2]) * int(_t(weight).shape[3]),
                 1, 1))))
        args.append(_t(bias))
    return run_op("deform_conv2d", f, *args)


class DeformConv2D:
    """Layer wrapper over deform_conv2d (reference vision/ops.py
    DeformConv2D)."""

    def __new__(cls, *args, **kwargs):
        from paddle_tpu.nn.layer.layers import Layer

        class _DeformConv2D(Layer):
            def __init__(self, in_channels, out_channels, kernel_size,
                         stride=1, padding=0, dilation=1,
                         deformable_groups=1, groups=1, weight_attr=None,
                         bias_attr=None):
                super().__init__()
                ks = (kernel_size, kernel_size) \
                    if isinstance(kernel_size, int) else tuple(kernel_size)
                rng = np.random.RandomState(0)
                bound = 1.0 / np.sqrt(in_channels * ks[0] * ks[1])
                self.weight = Parameter(rng.uniform(
                    -bound, bound,
                    (out_channels, in_channels // groups) + ks
                ).astype(np.float32))
                self.bias = None if bias_attr is False else Parameter(
                    np.zeros(out_channels, np.float32))
                self._cfg = (stride, padding, dilation, deformable_groups,
                             groups)

            def forward(self, x, offset, mask=None):
                s, p, d, dg, g = self._cfg
                return deform_conv2d(x, offset, self.weight, self.bias,
                                     s, p, d, dg, g, mask)
        obj = _DeformConv2D(*args, **kwargs)
        return obj


# ---------------------------------------------------------------------------
# proposals
# ---------------------------------------------------------------------------

def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Assign RoIs to FPN levels by scale (reference
    distribute_fpn_proposals)."""
    import paddle_tpu as paddle
    rois = _t(fpn_rois)
    arr = np.asarray(rois.numpy())
    off = 1.0 if pixel_offset else 0.0
    scale = np.sqrt(np.maximum(
        (arr[:, 2] - arr[:, 0] + off) * (arr[:, 3] - arr[:, 1] + off), 0))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    outs = []
    restore = np.zeros(len(arr), np.int32)
    pos = 0
    idx_all = []
    for L in range(min_level, max_level + 1):
        sel = np.nonzero(lvl == L)[0]
        outs.append(paddle.to_tensor(arr[sel].reshape(-1, 4)))
        idx_all.append(sel)
        restore[sel] = np.arange(pos, pos + len(sel))
        pos += len(sel)
    restore_ind = paddle.to_tensor(
        np.argsort(np.concatenate(idx_all)).astype(np.int32).reshape(-1, 1))
    if rois_num is not None:
        rois_num_per_level = [
            paddle.to_tensor(np.asarray([len(i)], np.int32))
            for i in idx_all]
        return outs, restore_ind, rois_num_per_level
    return outs, restore_ind


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference generate_proposals): decode
    anchors, clip, filter small, NMS."""
    import paddle_tpu as paddle
    sc = np.asarray(_t(scores).numpy())       # [N, A, H, W]
    bd = np.asarray(_t(bbox_deltas).numpy())  # [N, 4A, H, W]
    ims = np.asarray(_t(img_size).numpy())    # [N, 2]
    anc = np.asarray(_t(anchors).numpy()).reshape(-1, 4)
    var = np.asarray(_t(variances).numpy()).reshape(-1, 4)
    n = sc.shape[0]
    all_rois, all_nums = [], []
    for i in range(n):
        s = sc[i].transpose(1, 2, 0).reshape(-1)
        d = bd[i].reshape(-1, 4, sc.shape[2], sc.shape[3]) \
            .transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, a, v = s[order], d[order], anc[order % len(anc)], \
            var[order % len(var)]
        aw = a[:, 2] - a[:, 0]
        ah = a[:, 3] - a[:, 1]
        ax = a[:, 0] + aw / 2
        ay = a[:, 1] + ah / 2
        cx = v[:, 0] * d[:, 0] * aw + ax
        cy = v[:, 1] * d[:, 1] * ah + ay
        w = np.exp(np.minimum(v[:, 2] * d[:, 2], 10)) * aw
        h = np.exp(np.minimum(v[:, 3] * d[:, 3], 10)) * ah
        boxes = np.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                         -1)
        ih, iw = ims[i]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, iw - 1)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ih - 1)
        keep = ((boxes[:, 2] - boxes[:, 0]) >= min_size) & \
               ((boxes[:, 3] - boxes[:, 1]) >= min_size)
        boxes, s = boxes[keep], s[keep]
        if len(boxes):
            kept = nms(paddle.to_tensor(boxes.astype(np.float32)),
                       nms_thresh,
                       paddle.to_tensor(s.astype(np.float32)))
            ki = np.asarray(kept.numpy())[:post_nms_top_n]
            boxes = boxes[ki]
        all_rois.append(boxes.astype(np.float32))
        all_nums.append(len(boxes))
    rois = paddle.to_tensor(np.concatenate(all_rois, 0)
                            if all_rois else np.zeros((0, 4), np.float32))
    scores_out = paddle.to_tensor(
        np.concatenate([np.zeros(k, np.float32) for k in all_nums])
        if all_nums else np.zeros((0,), np.float32))
    if return_rois_num:
        return rois, scores_out, paddle.to_tensor(
            np.asarray(all_nums, np.int32))
    return rois, scores_out


# ---------------------------------------------------------------------------
# image IO
# ---------------------------------------------------------------------------

def read_file(filename, name=None):
    import paddle_tpu as paddle
    with open(filename, "rb") as fh:
        data = np.frombuffer(fh.read(), np.uint8)
    return paddle.to_tensor(data)


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor. Uses Pillow on host (the reference uses
    nvjpeg on device; TPU has no on-device decode — host decode + transfer
    is the idiomatic path, usually hidden in the input pipeline)."""
    import io
    import paddle_tpu as paddle
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("decode_jpeg requires Pillow") from e
    data = bytes(np.asarray(_t(x).numpy(), np.uint8))
    img = Image.open(io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode in ("rgb", "unchanged"):
        img = img.convert("RGB") if mode == "rgb" else img
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return paddle.to_tensor(arr)


class RoIPool:
    def __new__(cls, output_size, spatial_scale=1.0):
        from paddle_tpu.nn.layer.layers import Layer

        class _RoIPool(Layer):
            def __init__(self):
                super().__init__()

            def forward(self, x, boxes, boxes_num):
                return roi_pool(x, boxes, boxes_num, output_size,
                                spatial_scale)
        return _RoIPool()


class RoIAlign:
    def __new__(cls, output_size, spatial_scale=1.0):
        from paddle_tpu.nn.layer.layers import Layer

        class _RoIAlign(Layer):
            def __init__(self):
                super().__init__()

            def forward(self, x, boxes, boxes_num, aligned=True):
                return roi_align(x, boxes, boxes_num, output_size,
                                 spatial_scale, aligned=aligned)
        return _RoIAlign()


class PSRoIPool:
    def __new__(cls, output_size, spatial_scale=1.0):
        from paddle_tpu.nn.layer.layers import Layer

        class _PSRoIPool(Layer):
            def __init__(self):
                super().__init__()

            def forward(self, x, boxes, boxes_num):
                return psroi_pool(x, boxes, boxes_num, output_size,
                                  spatial_scale)
        return _PSRoIPool()


__all__ = [
    "yolo_loss", "yolo_box", "prior_box", "box_coder", "deform_conv2d",
    "DeformConv2D", "distribute_fpn_proposals", "generate_proposals",
    "read_file", "decode_jpeg", "roi_pool", "RoIPool", "psroi_pool",
    "PSRoIPool", "roi_align", "RoIAlign", "nms", "matrix_nms",
]
