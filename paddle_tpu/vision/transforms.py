"""paddle.vision.transforms equivalent (numpy/HWC based, reference:
python/paddle/vision/transforms/transforms.py)."""
from __future__ import annotations

import numbers
import random

import numpy as np

from paddle_tpu.core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] -> CHW float32 [0,1] Tensor."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        else:
            arr = arr.astype(np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW",
                 to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else \
            np.asarray(img, np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        out = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(out) if isinstance(img, Tensor) else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (list, tuple)) \
            else (size, size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        import jax
        import jax.numpy as jnp
        h, w = self.size
        if arr.ndim == 2:
            out = jax.image.resize(jnp.asarray(arr, jnp.float32), (h, w),
                                   "linear")
        else:
            out = jax.image.resize(
                jnp.asarray(arr, jnp.float32),
                (h, w, arr.shape[2]), "linear")
        out = np.asarray(out)
        return out.astype(arr.dtype) if arr.dtype == np.uint8 else out


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = size if isinstance(size, (list, tuple)) \
            else (size, size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            pads = [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pads)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return arr[i:i + th, j:j + tw]


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = size if isinstance(size, (list, tuple)) \
            else (size, size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        arr = np.asarray(img)
        if random.random() < self.prob:
            return arr[:, ::-1].copy()
        return arr


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        arr = np.asarray(img)
        if random.random() < self.prob:
            return arr[::-1].copy()
        return arr


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding
        self.fill = fill

    def _apply_image(self, img):
        arr = np.asarray(img)
        p = self.padding
        if isinstance(p, numbers.Number):
            p = (p, p, p, p)
        if len(p) == 2:
            p = (p[0], p[1], p[0], p[1])
        pads = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
        return np.pad(arr, pads, constant_values=self.fill)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW"):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def vflip(img):
    return np.asarray(img)[::-1].copy()


# ---------------------------------------------------------------------
# functional transforms (reference:
# python/paddle/vision/transforms/functional.py; numpy/HWC backend — the
# "cv2"/"pil" backends collapse to numpy here)
# ---------------------------------------------------------------------

def _np(img):
    if isinstance(img, Tensor):
        return img.numpy()
    return np.asarray(img)


def crop(img, top, left, height, width):
    return _np(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    a = _np(img)
    h, w = a.shape[:2]
    th, tw = output_size
    return crop(a, max((h - th) // 2, 0), max((w - tw) // 2, 0), th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    a = _np(img)
    if isinstance(padding, numbers.Number):
        pl = pr = pt = pb = int(padding)
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    cfg = [(pt, pb), (pl, pr)] + [(0, 0)] * (a.ndim - 2)
    if padding_mode == "constant":
        return np.pad(a, cfg, constant_values=fill)
    mode = {"edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    return np.pad(a, cfg, mode=mode)


def _affine_matrix(angle, translate, scale, shear, center):
    rot = np.deg2rad(angle)
    sx, sy = [np.deg2rad(s) for s in shear]
    cx, cy = center
    tx, ty = translate
    # M = T(center) T(translate) R(angle) Sh(shear) S(scale) T(-center)
    a = np.cos(rot - sy) / np.cos(sy)
    b = -np.cos(rot - sy) * np.tan(sx) / np.cos(sy) - np.sin(rot)
    c = np.sin(rot - sy) / np.cos(sy)
    d = -np.sin(rot - sy) * np.tan(sx) / np.cos(sy) + np.cos(rot)
    m = np.array([[a * scale, b * scale, 0],
                  [c * scale, d * scale, 0]], np.float64)
    m[0, 2] = cx + tx - m[0, 0] * cx - m[0, 1] * cy
    m[1, 2] = cy + ty - m[1, 0] * cx - m[1, 1] * cy
    return m


def _warp_affine(a, m, out_hw=None, fill=0.0):
    """Inverse-map affine warp with bilinear sampling (host-side numpy;
    input pipeline work like the reference's cv2 backend)."""
    h, w = a.shape[:2]
    oh, ow = out_hw if out_hw is not None else (h, w)
    minv = np.linalg.inv(np.vstack([m, [0, 0, 1]]))[:2]
    ys, xs = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
    src = minv @ np.stack([xs.ravel(), ys.ravel(),
                           np.ones(oh * ow)], 0)
    sx = src[0].reshape(oh, ow)
    sy = src[1].reshape(oh, ow)
    x0 = np.floor(sx).astype(np.int64)
    y0 = np.floor(sy).astype(np.int64)
    lx = sx - x0
    ly = sy - y0
    out = np.zeros((oh, ow) + a.shape[2:], np.float32)
    acc = a.astype(np.float32)
    for (yy, wy) in ((y0, 1 - ly), (y0 + 1, ly)):
        for (xx, wx) in ((x0, 1 - lx), (x0 + 1, lx)):
            valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
            yc = np.clip(yy, 0, h - 1)
            xc = np.clip(xx, 0, w - 1)
            wgt = (wy * wx * valid)
            out += acc[yc, xc] * (wgt[..., None] if a.ndim == 3 else wgt)
    if fill is not None and not (np.isscalar(fill) and fill == 0):
        none = ~(((y0 >= -1) & (y0 < h)) & ((x0 >= -1) & (x0 < w)))
        out[none] = fill
    return out.astype(a.dtype) if a.dtype != np.uint8 else \
        np.clip(out, 0, 255).astype(np.uint8)


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    a = _np(img)
    h, w = a.shape[:2]
    if center is None:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    m = _affine_matrix(angle, translate, scale, shear, center)
    return _warp_affine(a, m, fill=fill)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    a = _np(img)
    h, w = a.shape[:2]
    if center is None:
        center = ((w - 1) * 0.5, (h - 1) * 0.5)
    m = _affine_matrix(angle, (0, 0), 1.0, (0.0, 0.0), center)
    out_hw = None
    if expand:
        corners = np.array([[0, 0, 1], [w, 0, 1], [0, h, 1], [w, h, 1]]).T
        mapped = m @ corners
        ow = int(np.ceil(mapped[0].max() - mapped[0].min()))
        oh = int(np.ceil(mapped[1].max() - mapped[1].min()))
        m[0, 2] -= mapped[0].min()
        m[1, 2] -= mapped[1].min()
        out_hw = (oh, ow)
    return _warp_affine(a, m, out_hw, fill=fill)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    a = _np(img)
    # solve 8-dof homography from 4 point pairs
    A = []
    B = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        A.append([sx, sy, 1, 0, 0, 0, -ex * sx, -ex * sy])
        A.append([0, 0, 0, sx, sy, 1, -ey * sx, -ey * sy])
        B += [ex, ey]
    coef = np.linalg.lstsq(np.asarray(A, np.float64),
                           np.asarray(B, np.float64), rcond=None)[0]
    hmat = np.concatenate([coef, [1.0]]).reshape(3, 3)
    h, w = a.shape[:2]
    hinv = np.linalg.inv(hmat)
    ys, xs = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    pts = hinv @ np.stack([xs.ravel(), ys.ravel(), np.ones(h * w)], 0)
    sx = (pts[0] / pts[2]).reshape(h, w)
    sy = (pts[1] / pts[2]).reshape(h, w)
    x0 = np.round(sx).astype(np.int64)
    y0 = np.round(sy).astype(np.int64)
    valid = (x0 >= 0) & (x0 < w) & (y0 >= 0) & (y0 < h)
    out = np.full_like(a, fill)
    out[valid] = a[np.clip(y0, 0, h - 1), np.clip(x0, 0, w - 1)][valid]
    return out


def adjust_brightness(img, brightness_factor):
    a = _np(img).astype(np.float32)
    out = a * brightness_factor
    return np.clip(out, 0, 255).astype(np.uint8) \
        if _np(img).dtype == np.uint8 else out


def adjust_contrast(img, contrast_factor):
    a = _np(img).astype(np.float32)
    mean = a.mean() if a.ndim == 2 else \
        (0.299 * a[..., 0] + 0.587 * a[..., 1]
         + 0.114 * a[..., 2]).mean()
    out = a * contrast_factor + mean * (1 - contrast_factor)
    return np.clip(out, 0, 255).astype(np.uint8) \
        if _np(img).dtype == np.uint8 else out


def adjust_saturation(img, saturation_factor):
    a = _np(img).astype(np.float32)
    gray = (0.299 * a[..., 0] + 0.587 * a[..., 1]
            + 0.114 * a[..., 2])[..., None]
    out = a * saturation_factor + gray * (1 - saturation_factor)
    return np.clip(out, 0, 255).astype(np.uint8) \
        if _np(img).dtype == np.uint8 else out


def adjust_hue(img, hue_factor):
    a = _np(img).astype(np.float32) / 255.0 \
        if _np(img).dtype == np.uint8 else _np(img).astype(np.float32)
    r, g, b = a[..., 0], a[..., 1], a[..., 2]
    mx = np.max(a[..., :3], -1)
    mn = np.min(a[..., :3], -1)
    d = mx - mn + 1e-8
    hch = np.where(mx == r, ((g - b) / d) % 6,
                   np.where(mx == g, (b - r) / d + 2, (r - g) / d + 4)) / 6
    s = np.where(mx > 0, d / (mx + 1e-8), 0)
    v = mx
    hch = (hch + hue_factor) % 1.0
    i = np.floor(hch * 6)
    f = hch * 6 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = (i.astype(np.int64) % 6)[..., None]
    rgb = np.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    if _np(img).dtype == np.uint8:
        return np.clip(rgb * 255, 0, 255).astype(np.uint8)
    return rgb


def to_grayscale(img, num_output_channels=1):
    a = _np(img).astype(np.float32)
    gray = 0.299 * a[..., 0] + 0.587 * a[..., 1] + 0.114 * a[..., 2]
    out = np.repeat(gray[..., None], num_output_channels, -1)
    return out.astype(np.uint8) if _np(img).dtype == np.uint8 else out


def erase(img, i, j, h, w, v, inplace=False):
    if isinstance(img, Tensor):
        a = img.numpy().copy()
        # CHW tensor layout: a per-channel value broadcasts along the
        # leading channel axis, so lift (C,) -> (C, 1, 1)
        val = np.asarray(v)
        if val.ndim == 1:
            val = val.reshape(-1, 1, 1)
        a[..., i:i + h, j:j + w] = val
        return Tensor(a)
    a = _np(img).copy()
    a[i:i + h, j:j + w] = v
    return a


# ---------------------------------------------------------------------
# class transforms built on the functionals
# ---------------------------------------------------------------------

class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        a = np.asarray(img)
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]),
                                       np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if cw <= w and ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                patch = a[top:top + ch, left:left + cw]
                return resize(patch, self.size)
        return resize(center_crop(a, min(h, w)), self.size)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = list(range(4))
        random.shuffle(order)
        for i in order:
            img = self.transforms[i](img)
        return img


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None):
        self.degrees = (-degrees, degrees) \
            if isinstance(degrees, numbers.Number) else tuple(degrees)
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        a = np.asarray(img)
        h, w = a.shape[:2]
        angle = random.uniform(*self.degrees)
        tr = (0, 0)
        if self.translate:
            tr = (random.uniform(-self.translate[0], self.translate[0]) * w,
                  random.uniform(-self.translate[1], self.translate[1]) * h)
        sc = random.uniform(*self.scale) if self.scale else 1.0
        sh = (0.0, 0.0)
        if self.shear:
            if isinstance(self.shear, numbers.Number):
                sh = (random.uniform(-self.shear, self.shear), 0.0)
            elif len(self.shear) == 2:
                sh = (random.uniform(self.shear[0], self.shear[1]), 0.0)
            else:
                sh = (random.uniform(self.shear[0], self.shear[1]),
                      random.uniform(self.shear[2], self.shear[3]))
        return affine(a, angle, tr, sc, sh, fill=self.fill,
                      center=self.center)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0):
        self.degrees = (-degrees, degrees) \
            if isinstance(degrees, numbers.Number) else tuple(degrees)
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        return rotate(img, random.uniform(*self.degrees),
                      expand=self.expand, center=self.center,
                      fill=self.fill)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.fill = fill

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        a = np.asarray(img)
        h, w = a.shape[:2]
        d = self.distortion_scale
        tl = (random.randint(0, int(d * w / 2)),
              random.randint(0, int(d * h / 2)))
        tr = (w - 1 - random.randint(0, int(d * w / 2)),
              random.randint(0, int(d * h / 2)))
        br = (w - 1 - random.randint(0, int(d * w / 2)),
              h - 1 - random.randint(0, int(d * h / 2)))
        bl = (random.randint(0, int(d * w / 2)),
              h - 1 - random.randint(0, int(d * h / 2)))
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        return perspective(a, start, [tl, tr, br, bl], fill=self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        a = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
        if isinstance(img, Tensor):
            h, w = a.shape[-2:]
        else:
            h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = random.randint(0, h - eh)
                j = random.randint(0, w - ew)
                return erase(img, i, j, eh, ew, self.value)
        return img
