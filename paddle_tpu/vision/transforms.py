"""paddle.vision.transforms equivalent (numpy/HWC based, reference:
python/paddle/vision/transforms/transforms.py)."""
from __future__ import annotations

import numbers
import random

import numpy as np

from paddle_tpu.core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] -> CHW float32 [0,1] Tensor."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        else:
            arr = arr.astype(np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW",
                 to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else \
            np.asarray(img, np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        out = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(out) if isinstance(img, Tensor) else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (list, tuple)) \
            else (size, size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        import jax
        import jax.numpy as jnp
        h, w = self.size
        if arr.ndim == 2:
            out = jax.image.resize(jnp.asarray(arr, jnp.float32), (h, w),
                                   "linear")
        else:
            out = jax.image.resize(
                jnp.asarray(arr, jnp.float32),
                (h, w, arr.shape[2]), "linear")
        out = np.asarray(out)
        return out.astype(arr.dtype) if arr.dtype == np.uint8 else out


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = size if isinstance(size, (list, tuple)) \
            else (size, size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            pads = [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pads)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return arr[i:i + th, j:j + tw]


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = size if isinstance(size, (list, tuple)) \
            else (size, size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        arr = np.asarray(img)
        if random.random() < self.prob:
            return arr[:, ::-1].copy()
        return arr


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        arr = np.asarray(img)
        if random.random() < self.prob:
            return arr[::-1].copy()
        return arr


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding
        self.fill = fill

    def _apply_image(self, img):
        arr = np.asarray(img)
        p = self.padding
        if isinstance(p, numbers.Number):
            p = (p, p, p, p)
        if len(p) == 2:
            p = (p[0], p[1], p[0], p[1])
        pads = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
        return np.pad(arr, pads, constant_values=self.fill)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW"):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def vflip(img):
    return np.asarray(img)[::-1].copy()
