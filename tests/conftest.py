"""Test config: force a CPU backend with 8 virtual devices so collective /
sharding semantics are testable without TPU hardware (the reference's
gloo-on-CPU "fake cluster" trick, SURVEY §4.2).

The environment's axon shim (sitecustomize) registers a tunneled-TPU PJRT
backend whose client creation can block when the tunnel is unhealthy; tests
must never depend on it, so we hard-remove the axon/tpu factories and
restore jax's original backend lookup before the first op runs.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8")
if "xla_cpu_enable_concurrency_optimized_scheduler" not in _flags:
    # the concurrency-optimized CPU thunk scheduler issues data-
    # independent collectives in divergent per-device orders; with the
    # manual-tp zero-bubble pipelines (explicit collectives inside
    # cond-gated phases) that deadlocks the rendezvous (round 5 —
    # models/gpt_manual_tp.py). Sequential thunk scheduling restores
    # the uniform issue order. TPU is unaffected (per-core program
    # order is always uniform).
    _flags = (_flags
              + " --xla_cpu_enable_concurrency_optimized_scheduler=false")
os.environ["XLA_FLAGS"] = _flags.strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# the "tpu" factory stays registered (pop_tpu=False) — JAX_PLATFORMS=cpu
# already prevents backend creation, and popping it unregisters the
# "tpu" platform from MLIR, which breaks importing pallas kernels
from paddle_tpu._testing import unshim_axon  # noqa: E402

unshim_axon(pop_tpu=False)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# tests/ top level carries only test modules plus these two helpers.
# One-off measurement probes (the `_*.py` scripts that used to pollute
# the tests dir and its grep results) live in benchmarks/probes/ where
# pytest never collects them; this guard keeps it that way.
_ALLOWED_NON_TEST = {"conftest.py", "op_test.py"}
_strays = sorted(
    f for f in os.listdir(os.path.dirname(os.path.abspath(__file__)))
    if f.endswith(".py") and not f.startswith("test_")
    and f not in _ALLOWED_NON_TEST)
if _strays:
    raise RuntimeError(
        "non-test modules at tests/ top level: %s — move one-off "
        "probe scripts to benchmarks/probes/" % ", ".join(_strays))


@pytest.fixture(autouse=True)
def _seeded():
    import paddle_tpu
    paddle_tpu.seed(2024)
    np.random.seed(2024)
    yield


@pytest.fixture(autouse=True)
def _chaos_hygiene(request):
    """Fault-injection hygiene for `chaos`-marked tests (pytest.ini):
    installed rules and the arming env var never leak into later
    tests — a leaked persistent rule would fail every serving test
    after it."""
    yield
    if request.node.get_closest_marker("chaos") is not None:
        from paddle_tpu import _chaos
        _chaos.clear()
        os.environ.pop(_chaos.ENV, None)


# one log per session (pid-suffixed: concurrent sessions/users must not
# clobber each other's 'first leaker' diagnostic or hit foreign-owned
# /tmp files in fixture teardown)
DIRTY_STATE_LOG = f"/tmp/jax_dirty_state.{os.getpid()}.log"


@pytest.fixture(autouse=True, scope="session")
def _fresh_dirty_state_log():
    try:
        os.remove(DIRTY_STATE_LOG)
    except OSError:
        pass
    yield


@pytest.fixture(autouse=True)
def _jax_global_state_hygiene(request):
    """Record the FIRST test that leaves process-global jax state dirty
    (leaked disable_jit / trace context / x64): such a leak silently
    degrades every later test — the executable-count perf gate caught
    one as an order-dependent failure. Diagnostic log only; the leaker
    is fixed at the source."""
    yield
    from jax._src import core as _jcore
    dirty = []
    if jax.config.jax_disable_jit:
        dirty.append("jax_disable_jit")
    if jax.config.jax_enable_x64:
        dirty.append("jax_enable_x64")
    try:
        if not _jcore.trace_state_clean():
            dirty.append("trace_state")
    except Exception:
        pass
    if dirty:
        with open(DIRTY_STATE_LOG, "a") as f:
            f.write(f"{request.node.nodeid}: {dirty}\n")
