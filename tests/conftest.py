"""Test config: force a CPU backend with 8 virtual devices so collective /
sharding semantics are testable without TPU hardware (the reference's
gloo-on-CPU "fake cluster" trick, SURVEY §4.2).

The environment's axon shim (sitecustomize) registers a tunneled-TPU PJRT
backend whose client creation can block when the tunnel is unhealthy; tests
must never depend on it, so we hard-remove the axon/tpu factories and
restore jax's original backend lookup before the first op runs.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# the "tpu" factory stays registered (pop_tpu=False) — JAX_PLATFORMS=cpu
# already prevents backend creation, and popping it unregisters the
# "tpu" platform from MLIR, which breaks importing pallas kernels
from paddle_tpu._testing import unshim_axon  # noqa: E402

unshim_axon(pop_tpu=False)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seeded():
    import paddle_tpu
    paddle_tpu.seed(2024)
    np.random.seed(2024)
    yield
