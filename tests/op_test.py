"""OpTest harness (reference: test/legacy_test/op_test.py:418 —
check_output against a NumPy oracle in eager AND compiled modes,
check_grad against finite-difference numeric gradients
(get_numeric_gradient :148))."""
from __future__ import annotations

from typing import Callable, Dict, List, Sequence

import numpy as np
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


class OpTest:
    """Subclass and set:
      op:        callable taking Tensors (the paddle_tpu op)
      ref:       callable taking numpy arrays (oracle)
      inputs:    dict name -> np.ndarray
      attrs:     extra kwargs for both
      grad_inputs: names to grad-check (default: all float inputs)
    """

    op: Callable = None
    ref: Callable = None
    inputs: Dict[str, np.ndarray] = {}
    attrs: Dict = {}
    rtol = 1e-5
    atol = 1e-6
    grad_rtol = 5e-2
    grad_atol = 5e-3
    fd_eps = 1e-3

    # ------------------------------------------------------------------
    def _tensors(self, stop_gradient=True):
        return {k: paddle.to_tensor(v, stop_gradient=stop_gradient)
                for k, v in self.inputs.items()}

    def _run_op(self, tensors):
        return type(self).op(*tensors.values(), **self.attrs)

    def check_output(self, compiled=True):
        # eager
        out = self._run_op(self._tensors())
        ref_out = type(self).ref(*[np.asarray(v)
                                   for v in self.inputs.values()],
                                 **self.attrs)
        self._compare(out, ref_out, "eager")
        if compiled:
            op = type(self).op
            attrs = self.attrs
            names = list(self.inputs)

            def fn(*ts):
                return op(*ts, **attrs)
            static_fn = paddle.jit.to_static(fn, objs=[])
            out_c = static_fn(*self._tensors().values())
            self._compare(out_c, ref_out, "compiled")

    def _compare(self, out, ref_out, mode):
        outs = out if isinstance(out, (tuple, list)) else [out]
        refs = ref_out if isinstance(ref_out, (tuple, list)) else [ref_out]
        for i, (o, r) in enumerate(zip(outs, refs)):
            np.testing.assert_allclose(
                np.asarray(o.numpy(), np.float64)
                if o.dtype != np.bool_ else o.numpy(),
                np.asarray(r, np.float64)
                if np.asarray(r).dtype != np.bool_ else r,
                rtol=self.rtol, atol=self.atol,
                err_msg=f"{mode} output {i} mismatch")

    # ------------------------------------------------------------------
    def check_grad(self, grad_inputs: Sequence[str] = None,
                   output_index=0):
        names = list(grad_inputs or
                     [k for k, v in self.inputs.items()
                      if np.issubdtype(np.asarray(v).dtype, np.floating)])
        tensors = self._tensors(stop_gradient=False)
        for k in tensors:
            tensors[k].stop_gradient = k not in names
        out = self._run_op(tensors)
        out0 = (out[output_index]
                if isinstance(out, (tuple, list)) else out)
        out0.sum().backward()
        for name in names:
            analytic = tensors[name].grad.numpy().astype(np.float64)
            numeric = self._numeric_grad(name, output_index)
            np.testing.assert_allclose(
                analytic, numeric, rtol=self.grad_rtol,
                atol=self.grad_atol,
                err_msg=f"grad mismatch for input {name!r}")

    def _numeric_grad(self, name, output_index):
        """central finite differences of sum(op(...)[output_index])."""
        base = {k: np.asarray(v, np.float64).copy()
                for k, v in self.inputs.items()}
        x = base[name]
        grad = np.zeros_like(x)

        def f(vals):
            ts = {k: paddle.to_tensor(v.astype(self.inputs[k].dtype))
                  for k, v in vals.items()}
            out = self._run_op(ts)
            o = out[output_index] if isinstance(out, (tuple, list)) else out
            return float(np.asarray(o.numpy(), np.float64).sum())

        flat = x.reshape(-1)
        gflat = grad.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + self.fd_eps
            fp = f(base)
            flat[i] = orig - self.fd_eps
            fm = f(base)
            flat[i] = orig
            gflat[i] = (fp - fm) / (2 * self.fd_eps)
        return grad
