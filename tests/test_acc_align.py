"""Accuracy alignment vs torch (reference mechanism:
test/auto_parallel/hybrid_strategy/semi_auto_llama_acc_align.py — the
same model trained in two stacks must produce matching loss curves).

Here: the flagship hybrid-GPT training step (fp32) vs an identically
initialized torch GPT + torch AdamW on CPU, 5 steps, same data."""
import math

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax.numpy as jnp

from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models.gpt_hybrid import ParallelConfig, setup

CFG = dict(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
           max_seq_len=16)
LR, B1, B2, EPS, WD = 3e-4, 0.9, 0.95, 1e-8, 0.1
MEDIUM = dict(vocab_size=512, hidden_size=256, num_layers=4, num_heads=4,
              max_seq_len=128)


def torch_forward(p, ids, nh=None):
    x = p["wte"][ids] + p["wpe"][: ids.shape[1]][None]
    L = p["qkv_w"].shape[0]
    nh = nh if nh is not None else CFG["num_heads"]
    for i in range(L):
        h = F.layer_norm(x, (x.shape[-1],), p["ln1_g"][i], p["ln1_b"][i])
        qkv = h @ p["qkv_w"][i] + p["qkv_b"][i]
        q, k, v = qkv.chunk(3, dim=-1)
        b, s, hid = q.shape
        d = hid // nh
        q = q.view(b, s, nh, d).transpose(1, 2)
        k = k.view(b, s, nh, d).transpose(1, 2)
        v = v.view(b, s, nh, d).transpose(1, 2)
        att = (q @ k.transpose(-2, -1)) / math.sqrt(d)
        mask = torch.tril(torch.ones(s, s, dtype=torch.bool))
        att = att.masked_fill(~mask, float("-inf"))
        att = F.softmax(att, dim=-1)
        out = (att @ v).transpose(1, 2).reshape(b, s, hid)
        x = x + out @ p["proj_w"][i] + p["proj_b"][i]
        h = F.layer_norm(x, (x.shape[-1],), p["ln2_g"][i], p["ln2_b"][i])
        ff = F.gelu(h @ p["fc1_w"][i] + p["fc1_b"][i],
                    approximate="tanh") @ p["fc2_w"][i] + p["fc2_b"][i]
        x = x + ff
    x = F.layer_norm(x, (x.shape[-1],), p["lnf_g"], p["lnf_b"])
    return x @ p["wte"].T


def torch_loss(p, ids, nh=None):
    logits = torch_forward(p, ids, nh)[:, :-1]
    tgt = ids[:, 1:]
    return F.cross_entropy(logits.reshape(-1, logits.shape[-1]),
                           tgt.reshape(-1))



WIDTH_350M = dict(vocab_size=50304, hidden_size=1024, num_layers=24,
                  num_heads=8, max_seq_len=64)
# the bench.py flagship model WIDTH (GPT-1.3B: h=2048, 16x128 heads,
# V=50304). Depth reduced to L=6 and S=32 so the torch-CPU oracle stays
# tractable (full L=24 takes >25 min on CPU); width is what exercises
# the 16-head attention path and h=2048 init scaling.
WIDTH_1_3B = dict(vocab_size=50304, hidden_size=2048, num_layers=6,
                  num_heads=16, max_seq_len=32)


@pytest.mark.parametrize("name,cfg_d,seed,batch,steps,tol", [
    # toy: 5 steps, tight tolerance, strict-decrease check
    ("toy", CFG, 0, 2, 5, 2e-3),
    # non-toy width (h=256, L=4, S=128)
    ("medium", MEDIUM, 1, 2, 3, 5e-3),
    # 350M-class width/depth with reduced tokens (B2/S64)
    ("350m_width", WIDTH_350M, 3, 2, 3, 5e-3),
    # FULL flagship width/depth (the gpt1.3b bench.py model)
    ("bench_width_1_3b", WIDTH_1_3B, 4, 2, 3, 5e-3),
])
def test_loss_curve_matches_torch(name, cfg_d, seed, batch, steps, tol):
    """The same model trained in two stacks must produce matching loss
    curves (reference mechanism: semi_auto_llama_acc_align.py), at
    three scales up to the full bench parameterization."""
    import jax
    cfg = GPTConfig(**cfg_d)
    pcfg = ParallelConfig(dp=1, pp=1, tp=1, remat=False,
                          param_dtype=jnp.float32,
                          compute_dtype=jnp.float32)
    mesh, params, opt_state, step = setup(cfg, pcfg, seed=seed,
                                          devices=jax.devices("cpu")[:1])

    # mirror the jax params into torch leaves
    tp = {}
    flat = {"wte": params["wte"], "wpe": params["wpe"],
            "lnf_g": params["lnf_g"], "lnf_b": params["lnf_b"],
            **params["blocks"]}
    for k, v in flat.items():
        tp[k] = torch.tensor(np.asarray(v), dtype=torch.float32,
                             requires_grad=True)
    opt = torch.optim.AdamW(tp.values(), lr=LR, betas=(B1, B2),
                            eps=EPS, weight_decay=WD)

    ids = np.random.RandomState(seed).randint(
        0, cfg_d["vocab_size"], (batch, cfg_d["max_seq_len"]))
    jids = jnp.asarray(ids)
    tids = torch.tensor(ids, dtype=torch.long)

    jl, tl_ = [], []
    with mesh:
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state,
                                           (jids, jids))
            jl.append(float(loss))
    for _ in range(steps):
        opt.zero_grad()
        loss = torch_loss(tp, tids, nh=cfg_d["num_heads"])
        loss.backward()
        opt.step()
        tl_.append(float(loss.detach()))

    np.testing.assert_allclose(jl, tl_, rtol=tol, atol=tol)
    if name == "toy":
        # both curves strictly decreasing on this overfit toy
        assert jl[-1] < jl[0]
