"""Top-level API parity with the reference `paddle.__all__`
(reference: python/paddle/__init__.py) + numeric checks for the
compat op family (paddle_tpu/ops/compat.py)."""
import ast

import numpy as np
import pytest

import paddle_tpu as paddle

REF_INIT = "/root/reference/python/paddle/__init__.py"


def _ref_all():
    try:
        src = open(REF_INIT).read()
    except OSError:
        pytest.skip("reference tree unavailable")
    for node in ast.walk(ast.parse(src)):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if getattr(t, "id", "") == "__all__":
                    return [ast.literal_eval(e) for e in node.value.elts]
    return []


def test_top_level_all_covered():
    missing = [n for n in _ref_all() if not hasattr(paddle, n)]
    assert missing == [], f"missing top-level API: {missing}"


def test_block_diag_and_stacks():
    a = paddle.to_tensor([[1.0, 2.0]])
    b = paddle.to_tensor([[3.0]])
    out = paddle.block_diag([a, b]).numpy()
    np.testing.assert_allclose(out, [[1, 2, 0], [0, 0, 3]])
    c = paddle.column_stack([paddle.to_tensor([1.0, 2.0]),
                             paddle.to_tensor([3.0, 4.0])]).numpy()
    np.testing.assert_allclose(c, [[1, 3], [2, 4]])


def test_cartesian_prod_combinations_vander():
    cp = paddle.cartesian_prod([paddle.to_tensor([1, 2]),
                                paddle.to_tensor([3, 4])]).numpy()
    np.testing.assert_array_equal(cp, [[1, 3], [1, 4], [2, 3], [2, 4]])
    cmb = paddle.combinations(paddle.to_tensor([1, 2, 3])).numpy()
    np.testing.assert_array_equal(cmb, [[1, 2], [1, 3], [2, 3]])
    v = paddle.vander(paddle.to_tensor([1.0, 2.0]), 3).numpy()
    np.testing.assert_allclose(v, np.vander([1.0, 2.0], 3))


def test_splits_and_unflatten():
    x = paddle.rand([4, 6])
    parts = paddle.hsplit(x, 3)
    assert [p.shape for p in parts] == [[4, 2]] * 3
    parts = paddle.vsplit(x, 2)
    assert [p.shape for p in parts] == [[2, 6]] * 2
    assert paddle.unflatten(paddle.rand([2, 12]), 1, [3, 4]).shape == [2, 3, 4]


def test_scatter_family():
    out = paddle.slice_scatter(paddle.zeros([4, 4]), paddle.ones([2, 4]),
                               [0], [1], [3], [1]).numpy()
    assert out.sum() == 8 and out[0].sum() == 0
    out = paddle.select_scatter(paddle.zeros([2, 3]), paddle.ones([3]),
                                0, 1).numpy()
    np.testing.assert_allclose(out, [[0, 0, 0], [1, 1, 1]])
    out = paddle.diagonal_scatter(paddle.zeros([3, 3]),
                                  paddle.ones([3])).numpy()
    np.testing.assert_allclose(out, np.eye(3))


def test_math_compat_ops():
    np.testing.assert_array_equal(
        paddle.isin(paddle.to_tensor([1, 2, 3]),
                    paddle.to_tensor([2, 3])).numpy(), [False, True, True])
    np.testing.assert_allclose(
        paddle.pdist(paddle.to_tensor([[0.0, 0.0], [3.0, 4.0]])).numpy(),
        [5.0], rtol=1e-5)
    np.testing.assert_allclose(
        float(paddle.trapezoid(paddle.to_tensor([1.0, 2.0, 3.0])).numpy()),
        4.0)
    np.testing.assert_allclose(
        paddle.cumulative_trapezoid(
            paddle.to_tensor([1.0, 2.0, 3.0])).numpy(), [1.5, 4.0])
    m, e = paddle.frexp(paddle.to_tensor([8.0]))
    assert float(m.numpy()[0]) == 0.5 and int(e.numpy()[0]) == 4
    np.testing.assert_allclose(
        paddle.ldexp(paddle.to_tensor([1.0]),
                     paddle.to_tensor([3])).numpy(), [8.0])
    # multigammaln vs scipy-free reference: Γ_2(5) where
    # log Γ_2(a) = 0.5 log π + lgamma(a) + lgamma(a - 0.5)
    import math
    want = 0.5 * math.log(math.pi) + math.lgamma(5.0) + math.lgamma(4.5)
    got = float(paddle.multigammaln(paddle.to_tensor([5.0]), 2).numpy()[0])
    assert abs(got - want) < 1e-3
    np.testing.assert_array_equal(
        paddle.signbit(paddle.to_tensor([-1.0, 1.0])).numpy(), [True, False])
    np.testing.assert_allclose(
        paddle.sgn(paddle.to_tensor([-3.0, 0.0, 2.0])).numpy(), [-1, 0, 1])


def test_inplace_variants_autograd():
    w = paddle.to_tensor([2.0, 3.0])
    w.stop_gradient = False
    out = paddle.tanh(w)
    paddle.square_(out)
    out.backward()
    th = np.tanh([2.0, 3.0])
    np.testing.assert_allclose(w.grad.numpy(), 2 * th * (1 - th ** 2),
                               rtol=1e-2)


def test_inplace_variants_values():
    a = paddle.to_tensor([1.0, 4.0])
    paddle.sqrt_(a)
    np.testing.assert_allclose(a.numpy(), [1.0, 2.0])
    b = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    paddle.t_(b)
    np.testing.assert_allclose(b.numpy(), [[1, 3], [2, 4]])
    c = paddle.to_tensor([1, 2, 3])
    paddle.equal_(c, paddle.to_tensor([1, 0, 3]))
    np.testing.assert_array_equal(c.numpy(), [True, False, True])
    d = paddle.zeros([64])
    paddle.log_normal_(d)
    assert (d.numpy() > 0).all()
    e = paddle.zeros([64])
    paddle.geometric_(e, 0.5)
    assert e.numpy().min() >= 1


def test_framework_helpers():
    x = paddle.rand([2, 3])
    assert int(paddle.rank(x).numpy()) == 2
    assert paddle.is_floating_point(x)
    assert not paddle.is_integer(x)
    assert not paddle.is_complex(x)
    assert paddle.tolist(paddle.to_tensor([1, 2])) == [1, 2]
    p = paddle.create_parameter([2, 3], "float32")
    assert not p.stop_gradient and p.shape == [2, 3]
    st = paddle.get_rng_state()
    paddle.set_rng_state(st)
    b = paddle.batch(lambda: iter(range(7)), 3, drop_last=True)
    assert [len(v) for v in b()] == [3, 3]
    with paddle.LazyGuard():
        pass
    assert paddle.flops(paddle.nn.Linear(4, 8), [2, 4]) > 0


class TestTensorMethodSurface:
    def test_reference_method_list_fully_bound(self):
        """Every method in the reference's tensor_method_func list
        (python/paddle/tensor/__init__.py) exists on Tensor."""
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.ops.tensor_methods import (
            REFERENCE_TENSOR_METHODS)
        missing = [m for m in REFERENCE_TENSOR_METHODS
                   if not hasattr(Tensor, m)]
        assert missing == [], missing

    def test_patched_methods_route_self_first(self):
        t = paddle.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]],
                                      np.float32))
        np.testing.assert_allclose(
            t.cdist(t).numpy(),
            [[0, np.sqrt(8)], [np.sqrt(8), 0]], rtol=1e-5, atol=1e-6)
        a = paddle.to_tensor(np.zeros(3, np.float32))
        a.lerp_(paddle.to_tensor(np.ones(3, np.float32)), 0.5)
        np.testing.assert_allclose(a.numpy(), 0.5)
        tr = paddle.to_tensor(np.arange(6, dtype=np.float32)
                              .reshape(2, 3))
        tr.transpose_([1, 0])
        assert tr.shape == [3, 2]

    def test_top_p_sampling(self):
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(2, 16).astype(np.float32))
        ps = paddle.to_tensor(np.array([0.9, 0.5], np.float32))
        scores, ids = paddle.top_p_sampling(x, ps)
        assert ids.numpy().shape == (2, 1)
        assert (ids.numpy() >= 0).all() and (ids.numpy() < 16).all()
        # p -> 0 degenerates to argmax
        ps0 = paddle.to_tensor(np.array([1e-6, 1e-6], np.float32))
        _, ids0 = paddle.top_p_sampling(x, ps0)
        np.testing.assert_array_equal(
            ids0.numpy().ravel(), x.numpy().argmax(-1))
