"""Tests for the reader/dataset/callbacks/decomposition/jit-export API
surface (reference analogs: python/paddle/{batch,reader,dataset,
callbacks,decomposition}.py and jit save/load -> TranslatedLayer)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


class TestBatchAndReader:
    def test_batch(self):
        r = paddle.batch(lambda: iter(range(10)), 3)
        assert [len(b) for b in r()] == [3, 3, 3, 1]
        r = paddle.batch(lambda: iter(range(10)), 3, drop_last=True)
        assert [len(b) for b in r()] == [3, 3, 3]

    def test_reader_decorators(self):
        import paddle_tpu.reader as reader
        assert sorted(reader.shuffle(lambda: iter(range(20)), 5)()) == \
            list(range(20))
        assert list(reader.chain(lambda: iter([1, 2]),
                                 lambda: iter([3]))()) == [1, 2, 3]
        assert list(reader.compose(lambda: iter([1, 2]),
                                   lambda: iter([3, 4]))()) == \
            [(1, 3), (2, 4)]
        with pytest.raises(reader.ComposeNotAligned):
            list(reader.compose(lambda: iter([1]),
                                lambda: iter([3, 4]))())
        assert list(reader.firstn(lambda: iter(range(10)), 4)()) == \
            [0, 1, 2, 3]
        assert list(reader.buffered(lambda: iter(range(6)), 2)()) == \
            list(range(6))
        cached = reader.cache(lambda: iter(range(5)))
        assert list(cached()) == list(cached()) == list(range(5))
        out = list(reader.xmap_readers(lambda x: x * 2,
                                       lambda: iter(range(8)),
                                       3, 4, order=True)())
        assert out == [0, 2, 4, 6, 8, 10, 12, 14]
        out = list(reader.multiprocess_reader(
            [lambda: iter([1, 2]), lambda: iter([3, 4])])())
        assert sorted(out) == [1, 2, 3, 4]

    def test_dataset_readers(self):
        import paddle_tpu.dataset as ds
        im, lb = next(ds.mnist.train()())
        assert im.shape == (784,) and im.dtype == np.float32
        x, y = next(ds.uci_housing.train()())
        assert x.shape == (13,) and y.shape == (1,)
        im, lb = next(ds.cifar.train10()())
        assert im.shape == (3072,)
        ids, lab = next(ds.imdb.train()())
        assert isinstance(ids, list) and lab in (0, 1)
        src, trg, nxt = next(ds.wmt16.train(1000, 1000)())
        assert len(nxt) == len(trg)


class TestDecomposition:
    def test_rules_match_ops(self):
        import jax.numpy as jnp
        import paddle_tpu.decomposition as dc
        a = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        sm = np.asarray(dc.get_decomp_rule("softmax")(jnp.asarray(a)))
        ref = paddle.nn.functional.softmax(
            paddle.to_tensor(a), axis=-1).numpy()
        np.testing.assert_allclose(sm, ref, rtol=1e-5)
        ln = np.asarray(dc.get_decomp_rule("layer_norm")(jnp.asarray(a)))
        ref = paddle.nn.functional.layer_norm(
            paddle.to_tensor(a), normalized_shape=[8]).numpy()
        np.testing.assert_allclose(ln, ref, rtol=1e-4, atol=1e-5)

    def test_prim_guard(self):
        import paddle_tpu.decomposition as dc
        assert not dc.prim_enabled()
        with dc.prim_guard():
            assert dc.prim_enabled()
        assert not dc.prim_enabled()

    def test_decompose_whitelist_validation(self):
        import paddle_tpu.decomposition as dc
        with pytest.raises(ValueError):
            dc.decompose(None, whitelist={"not_a_real_op"})


class TestHermitianFFT:
    def test_hfftn_vs_scipy(self):
        import scipy.fft as sfft
        rs = np.random.RandomState(0)
        a = (rs.randn(4, 6) + 1j * rs.randn(4, 6)).astype(np.complex64)
        for norm in ("backward", "forward", "ortho"):
            mine = paddle.fft.hfftn(paddle.to_tensor(a), norm=norm).numpy()
            np.testing.assert_allclose(mine, sfft.hfftn(a, norm=norm),
                                       rtol=2e-4, atol=1e-4)
            r = rs.randn(4, 6).astype(np.float32)
            mine = paddle.fft.ihfftn(paddle.to_tensor(r),
                                     norm=norm).numpy()
            np.testing.assert_allclose(mine, sfft.ihfftn(r, norm=norm),
                                       rtol=2e-4, atol=1e-4)


class TestLinalgAdditions:
    def test_matrix_exp(self):
        import scipy.linalg as sla
        a = np.random.RandomState(0).randn(4, 4).astype(np.float32) * 0.3
        mine = paddle.linalg.matrix_exp(paddle.to_tensor(a)).numpy()
        np.testing.assert_allclose(mine, sla.expm(a), rtol=1e-4,
                                   atol=1e-5)

    def test_fp8_gemm(self):
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(8, 16).astype(np.float32))
        y = paddle.to_tensor(rs.randn(16, 8).astype(np.float32))
        out = paddle.linalg.fp8_fp8_half_gemm_fused(
            x, y, output_dtype="bfloat16")
        assert out.numpy().shape == (8, 8)
        # fp8 quantization error is large; just check correlation
        ref = x.numpy() @ y.numpy()
        got = out.numpy().astype(np.float32)
        cc = np.corrcoef(ref.ravel(), got.ravel())[0, 1]
        assert cc > 0.98, cc


class TestSavedTensorsHooks:
    def test_pack_unpack(self):
        from paddle_tpu.autograd import PyLayer, saved_tensors_hooks
        events = []

        class Double(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * 2

            @staticmethod
            def backward(ctx, g):
                (x,) = ctx.saved_tensor
                return g * 2

        def pack(t):
            events.append("pack")
            return t.numpy()          # e.g. offload to host

        def unpack(p):
            events.append("unpack")
            return paddle.to_tensor(p)

        with saved_tensors_hooks(pack, unpack):
            x = paddle.to_tensor(np.ones(3, np.float32),
                                 stop_gradient=False)
            Double.apply(x).sum().backward()
        assert events == ["pack", "unpack"]
        np.testing.assert_allclose(x.grad.numpy(), 2 * np.ones(3))


class TestJitExport:
    def test_save_load_translated_layer(self, tmp_path):
        from paddle_tpu.jit import InputSpec, TranslatedLayer
        lin = nn.Linear(4, 2)
        path = str(tmp_path / "m")
        paddle.jit.save(lin, path, input_spec=[InputSpec([1, 4],
                                                         "float32")])
        tl = paddle.jit.load(path)
        assert isinstance(tl, TranslatedLayer)
        x = np.random.RandomState(0).randn(1, 4).astype(np.float32)
        np.testing.assert_allclose(tl(paddle.to_tensor(x)).numpy(),
                                   lin(paddle.to_tensor(x)).numpy(),
                                   rtol=1e-5)
        assert set(tl.state_dict()) == set(lin.state_dict())
        with pytest.raises(RuntimeError):
            tl.train()


class TestGeometricSampling:
    def test_sample_and_reindex(self):
        row = paddle.to_tensor([3, 7, 0, 9, 1, 4, 2, 9, 3, 9, 1, 9, 7],
                               dtype="int64")
        colptr = paddle.to_tensor([0, 2, 4, 5, 6, 7, 9, 11, 11, 13, 13],
                                  dtype="int64")
        nodes = paddle.to_tensor([0, 8, 1, 2], dtype="int64")
        n, c = paddle.geometric.sample_neighbors(row, colptr, nodes,
                                                 sample_size=2)
        assert c.numpy().tolist() == [2, 2, 2, 1]
        x = paddle.to_tensor([0, 1, 2], dtype="int64")
        nb = paddle.to_tensor([8, 9, 0, 4, 7, 6, 7], dtype="int64")
        ct = paddle.to_tensor([2, 3, 2], dtype="int32")
        s, d, o = paddle.geometric.reindex_graph(x, nb, ct)
        assert s.numpy().tolist() == [3, 4, 0, 5, 6, 7, 6]
        assert d.numpy().tolist() == [0, 0, 1, 1, 1, 2, 2]
        assert o.numpy().tolist() == [0, 1, 2, 8, 9, 4, 7, 6]


class TestCallbacks:
    def test_reduce_lr_on_plateau(self):
        import paddle_tpu.callbacks as cb
        lin = nn.Linear(2, 2)
        opt = paddle.optimizer.SGD(learning_rate=1.0,
                                   parameters=lin.parameters())
        c = cb.ReduceLROnPlateau(monitor="loss", factor=0.5, patience=1,
                                 verbose=0)
        c.set_model(type("M", (), {"_optimizer": opt})())
        c.on_eval_end({"loss": 1.0})
        c.on_eval_end({"loss": 1.0})   # wait=1 >= patience -> reduce
        assert abs(opt.get_lr() - 0.5) < 1e-9

    def test_visualdl_writes_scalars(self, tmp_path):
        import json
        import paddle_tpu.callbacks as cb
        v = cb.VisualDL(str(tmp_path))
        v.on_train_batch_end(0, {"loss": 1.5})
        v.on_train_end()
        lines = [json.loads(ln) for ln in
                 open(tmp_path / "scalars.jsonl")]
        assert lines[0]["tag"] == "train/loss"


class TestGraphBreakFallback:
    def test_untraceable_fn_falls_back_to_eager(self):
        import warnings
        from paddle_tpu import nn
        lin = nn.Linear(4, 4)

        def untraceable(x):
            if float(x.sum().numpy()) > 0:   # data-dependent branch
                return lin(x) * 2
            return lin(x)

        f = paddle.jit.to_static(untraceable, objs=[lin])
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            out = f(x)
        assert any("falling back to eager" in str(m.message) for m in w)
        np.testing.assert_allclose(out.numpy(), f(x).numpy())

    def test_traceable_fn_still_compiles(self):
        from paddle_tpu import nn
        lin = nn.Linear(4, 4)
        g = paddle.jit.to_static(lambda x: lin(x) + 1, objs=[lin])
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        np.testing.assert_allclose(g(x).numpy(), lin(x).numpy() + 1,
                                   rtol=1e-5)


class TestDynamicShapeExport:
    def test_saved_program_serves_any_batch(self, tmp_path):
        """-1 dims in InputSpec export as symbolic dims (the shape
        dialect role): one saved program serves every batch size."""
        from paddle_tpu.jit import InputSpec
        lin = nn.Linear(4, 2)
        path = str(tmp_path / "dyn")
        paddle.jit.save(lin, path,
                        input_spec=[InputSpec([-1, 4], "float32")])
        tl = paddle.jit.load(path)
        for b in (1, 3, 17):
            x = np.random.RandomState(b).randn(b, 4).astype(np.float32)
            np.testing.assert_allclose(
                tl(paddle.to_tensor(x)).numpy(),
                lin(paddle.to_tensor(x)).numpy(), rtol=1e-5)


class TestIndexOf:
    def test_first_flat_hit_and_missing(self):
        from paddle_tpu.ops.search import index_of
        x = paddle.to_tensor(np.array([[3, 1], [2, 1]], np.int64))
        assert int(index_of(x, 1)) == 1          # first flat occurrence
        assert int(index_of(x, 2)) == 2
        import pytest as _pytest
        with _pytest.raises(ValueError, match="not in tensor"):
            index_of(x, 9)
