"""Runtime attention-kernel autotune (ops/pallas/autotune.py).

Reference mechanism: phi/kernels/autotune — time each candidate once,
cache the winner by shape key, reuse. Measurement itself needs a TPU;
here the timing hook is stubbed and the choice logic, shape gating,
persistence, and dispatch precedence are verified on CPU.
"""
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import autotune


@pytest.fixture(autouse=True)
def _fresh_table(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_CACHE_DIR", str(tmp_path))
    monkeypatch.setattr(autotune, "_table", None)
    yield


def test_candidates_shape_gating():
    # S=512, D=128: whole-slice simple kernel feasible
    c = autotune.candidates((2, 512, 8, 128), 512, jnp.bfloat16, True)
    assert c[0] == "simple" and "xla" in c and "library_flash" in c
    # S=2048 bf16: whole [S,S] f32 scores no longer fit VMEM
    c = autotune.candidates((2, 2048, 8, 128), 2048, jnp.bfloat16, True)
    assert "simple" not in c
    assert "causal_skip" in c or "qblock" in c
    # S=4096: every monolithic Pallas gate rejects; only the streaming
    # kernels remain — the q×kv-blocked variants (block sizes in the
    # candidate name), the library kernel, and xla
    c = autotune.candidates((2, 4096, 8, 128), 4096, jnp.bfloat16, True)
    assert not {"simple", "causal_skip", "qblock"} & set(c)
    assert "blocked_bq512_bkv512" in c and "blocked_bq256_bkv512" in c
    assert c.index("blocked_bq512_bkv512") < c.index("library_flash")
    # non-causal drops the causal-skip kernel
    c = autotune.candidates((2, 2048, 8, 128), 2048, jnp.bfloat16, False)
    assert "causal_skip" not in c
    # cross attention (S != Skv): streaming kernels only (the blocked
    # kernel takes non-causal cross-attn; causal cross-attn it gates
    # out)
    c = autotune.candidates((2, 512, 8, 128), 1024, jnp.bfloat16, False)
    assert not {"simple", "causal_skip", "qblock"} & set(c)
    assert "blocked_bq512_bkv512" in c
    c = autotune.candidates((2, 512, 8, 128), 1024, jnp.bfloat16, True)
    assert not any(n.startswith("blocked") for n in c)
    # odd head dim: xla only
    c = autotune.candidates((2, 512, 8, 80), 512, jnp.float32, True)
    assert c == ["xla"]


def test_measure_picks_fastest_and_persists(monkeypatch):
    fake = {"simple": 2.0, "causal_skip": 0.5, "qblock": 1.0,
            "library_flash": 3.0, "xla": 9.0}
    # blocked_bq*_bkv* variants and any future candidate: slower
    monkeypatch.setattr(autotune, "_time_candidate",
                        lambda name, *a, **k: fake.get(name, 7.0))
    monkeypatch.setattr(autotune, "_device_kind", lambda: "testchip")
    win = autotune.measure((2, 2048, 8, 128), 2048, jnp.bfloat16, True)
    assert win == "causal_skip"
    # persisted
    with open(autotune._cache_path()) as f:
        tab = json.load(f)
    (key,) = tab.keys()
    assert key.startswith("testchip|") and "causal=True" in key
    assert tab[key]["winner"] == "causal_skip"
    assert tab[key]["timings_ms"]["xla"] == 9000.0
    # second measure: answered from the table, no re-timing
    monkeypatch.setattr(autotune, "_time_candidate",
                        lambda *a, **k: pytest.fail("re-timed"))
    assert autotune.measure((2, 2048, 8, 128), 2048,
                            jnp.bfloat16, True) == "causal_skip"


def test_lookup_reloads_from_disk(monkeypatch):
    monkeypatch.setattr(autotune, "_device_kind", lambda: "testchip")
    monkeypatch.setattr(autotune, "_time_candidate",
                        lambda name, *a, **k: 1.0 if name == "qblock"
                        else 5.0)
    autotune.measure((1, 1024, 4, 128), 1024, jnp.float32, True)
    autotune._table = None          # fresh process simulation
    assert autotune.lookup((1, 1024, 4, 128), 1024,
                           jnp.float32, True) == "qblock"


def test_decide_trace_time_is_table_only(monkeypatch):
    monkeypatch.setattr(autotune, "_device_kind", lambda: "testchip")
    calls = []

    def fake_measure(*a, **k):
        calls.append(a)
        return "simple"

    monkeypatch.setattr(autotune, "measure", fake_measure)

    got = {}

    def probe(q, k):
        got["ans"] = autotune.decide(q, k, True)
        return q

    q = jnp.zeros((2, 512, 8, 128), jnp.float32)
    jax.jit(probe)(q, q)
    # tracer + empty table: no measurement, static chain decides
    assert got["ans"] is None and not calls

    # seed the table; the same traced dispatch now answers from it
    autotune._load_table()["testchip|B2S512H8D128Skv512|float32|"
                          "causal=True"] = {"winner": "qblock"}
    jax.jit(lambda a, b: probe(a, b))(q, q)
    assert got["ans"] == "qblock"


def test_decide_cpu_backend_never_measures(monkeypatch):
    calls = []
    monkeypatch.setattr(autotune, "measure",
                        lambda *a, **k: calls.append(a) or "simple")
    q = jnp.zeros((2, 512, 8, 128), jnp.float32)
    assert autotune.decide(q, q, True) is None
    assert not calls                # backend is cpu in the test env


def test_blocked_candidate_name_roundtrip():
    # the winner cache pins (kernel, bq, bkv) through the name alone
    assert autotune.blocked_name(512, 1024) == "blocked_bq512_bkv1024"
    assert callable(autotune._resolve("blocked_bq128_bkv256"))
    with pytest.raises(KeyError):
        autotune._resolve("blocked_bq128")      # malformed: not a
    with pytest.raises(KeyError):               # known static runner
        autotune._resolve("no_such_kernel")


def test_corrupted_cache_falls_back_to_static_chain(monkeypatch):
    import os
    path = autotune._cache_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    # a partial/interleaved write: invalid JSON
    with open(path, "w") as f:
        f.write('{"v5e|B2S4096H8D128Skv4096|bfloat16|causal=Tr')
    assert autotune._load_table() == {}
    assert autotune.lookup((2, 4096, 8, 128), 4096,
                           jnp.bfloat16, True) is None
    # decide() on the corrupted table: None -> static chain
    q = jnp.zeros((2, 512, 8, 128), jnp.float32)
    assert autotune.decide(q, q, True) is None
    # valid JSON, wrong schema (hand-edited / foreign tool): each bad
    # entry degrades to the static chain instead of crashing dispatch
    monkeypatch.setattr(autotune, "_device_kind", lambda: "testchip")
    key = autotune._key((1, 512, 4, 128), 512, jnp.float32, True)
    autotune._table = None
    with open(path, "w") as f:
        json.dump({key: "qblock", "other": {"no_winner": 1}}, f)
    assert autotune.lookup((1, 512, 4, 128), 512,
                           jnp.float32, True) is None
    monkeypatch.setattr(autotune, "_time_candidate",
                        lambda name, *a, **k: 1.0 if name == "simple"
                        else 5.0)
    # measure() over a wrong-schema entry re-measures and rewrites it
    # (it must not trust the unvalidated cache hit)
    assert autotune.measure((1, 512, 4, 128), 512,
                            jnp.float32, True) == "simple"
    # measure() over the top of a corrupted file rewrites it valid
    autotune._table = None
    with open(path, "w") as f:
        f.write("not json at all")
    assert autotune.measure((1, 512, 4, 128), 512,
                            jnp.float32, True) == "simple"
    with open(path) as f:
        assert json.load(f)[key]["winner"] == "simple"


def test_concurrent_writers_merge_not_clobber(monkeypatch):
    """Two processes measuring different shapes on one host: each save
    is atomic (temp + os.replace, no partial interleave) and re-merges
    the file, so neither winner is lost whatever the write order."""
    monkeypatch.setattr(autotune, "_device_kind", lambda: "testchip")
    monkeypatch.setattr(autotune, "_time_candidate",
                        lambda name, *a, **k: 1.0 if name == "simple"
                        else 5.0)
    key_a = autotune._key((1, 512, 4, 128), 512, jnp.float32, True)
    key_b = autotune._key((2, 512, 4, 128), 512, jnp.float32, True)
    # process A measures shape A and persists
    autotune.measure((1, 512, 4, 128), 512, jnp.float32, True)
    # process B loaded BEFORE A's write (empty table), measures shape
    # B, then persists — without merge-on-save this would clobber A
    autotune._table = {}
    autotune.measure((2, 512, 4, 128), 512, jnp.float32, True)
    with open(autotune._cache_path()) as f:
        tab = json.load(f)              # file is valid JSON throughout
    assert tab[key_a]["winner"] == "simple"
    assert tab[key_b]["winner"] == "simple"
    # a reader process (fresh table) sees both winners
    autotune._table = None
    assert autotune.lookup((1, 512, 4, 128), 512,
                           jnp.float32, True) == "simple"
    assert autotune.lookup((2, 512, 4, 128), 512,
                           jnp.float32, True) == "simple"


def test_runner_numerics_xla_vs_simple_interpret():
    """The xla candidate (the baseline every kernel is timed against)
    must agree with the interpreted simple kernel."""
    from paddle_tpu.ops.pallas import simple_attention as sa
    rng = np.random.RandomState(0)
    b, s, h, d = 1, 128, 2, 128
    q = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    xla = autotune._runners()["xla"](q, k, v, True, None)
    qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    ref = jnp.swapaxes(
        sa.attention_bhsd(qt, kt, vt, causal=True, interpret=True), 1, 2)
    np.testing.assert_allclose(np.asarray(xla), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
