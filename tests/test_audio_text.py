"""Audio feature + text ViterbiDecoder numerics (reference analogs:
test/legacy_test/test_audio_functions.py, test_viterbi_decode.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestAudioFunctional:
    def test_mel_hz_roundtrip(self):
        import paddle_tpu.audio.functional as AF
        mel = AF.hz_to_mel(440.0)
        assert abs(AF.mel_to_hz(mel) - 440.0) < 1e-3

    def test_fbank_matrix_shape_and_coverage(self):
        import paddle_tpu.audio.functional as AF
        fb = AF.compute_fbank_matrix(sr=16000, n_fft=512, n_mels=40)
        arr = fb.numpy() if hasattr(fb, "numpy") else np.asarray(fb)
        assert arr.shape == (40, 257)
        # every mel filter has some support
        assert (arr.sum(1) > 0).all()

    def test_spectrogram_matches_scipy_stft_power(self):
        import scipy.signal as ss
        import paddle_tpu.audio.features as AFt
        sr, n_fft, hop = 16000, 512, 160
        t = np.arange(sr // 4) / sr
        wav = np.sin(2 * np.pi * 1000 * t).astype(np.float32)
        spec = AFt.Spectrogram(n_fft=n_fft, hop_length=hop,
                               window="hann", power=2.0)
        out = spec(paddle.to_tensor(wav[None])).numpy()[0]
        # peak frequency bin ~ 1000 Hz
        peak = out.mean(-1).argmax()
        expected_bin = round(1000 * n_fft / sr)
        assert abs(int(peak) - expected_bin) <= 1, (peak, expected_bin)

    def test_mfcc_shape(self):
        import paddle_tpu.audio.features as AFt
        wav = np.random.RandomState(0).randn(1, 8000).astype(np.float32)
        m = AFt.MFCC(sr=16000, n_mfcc=13)
        out = m(paddle.to_tensor(wav)).numpy()
        assert out.shape[1] == 13

    def test_power_to_db_clamps(self):
        import paddle_tpu.audio.functional as AF
        s = paddle.to_tensor(np.array([1.0, 1e-12], np.float32))
        db = AF.power_to_db(s)
        arr = db.numpy() if hasattr(db, "numpy") else np.asarray(db)
        assert arr[0] - arr[1] <= 80.0 + 1e-5


class TestTextViterbi:
    def test_viterbi_decode_matches_bruteforce(self):
        from paddle_tpu.text import viterbi_decode
        rs = np.random.RandomState(0)
        B, T, N = 2, 4, 3
        emit = rs.randn(B, T, N).astype(np.float32)
        trans = rs.randn(N, N).astype(np.float32)
        lens = np.array([4, 3], np.int64)
        scores, paths = viterbi_decode(
            paddle.to_tensor(emit), paddle.to_tensor(trans),
            paddle.to_tensor(lens), include_bos_eos_tag=False)

        # brute force over all tag sequences
        import itertools
        for b in range(B):
            L = lens[b]
            best, best_path = -1e30, None
            for seq in itertools.product(range(N), repeat=int(L)):
                sc = emit[b, 0, seq[0]]
                for t in range(1, L):
                    sc += trans[seq[t - 1], seq[t]] + emit[b, t, seq[t]]
                if sc > best:
                    best, best_path = sc, seq
            np.testing.assert_allclose(float(scores.numpy()[b]), best,
                                       rtol=1e-4)
            assert paths.numpy()[b][:L].tolist() == list(best_path)


class TestSignalStft:
    def test_stft_matches_scipy(self):
        import scipy.signal as ss
        x = np.sin(2 * np.pi * 440 * np.arange(4000) / 16000) \
            .astype(np.float32)
        n_fft, hop = 512, 128
        out = paddle.signal.stft(paddle.to_tensor(x[None]), n_fft=n_fft,
                                 hop_length=hop, center=True,
                                 pad_mode="reflect").numpy()[0]
        _, _, ref = ss.stft(x, nperseg=n_fft, noverlap=n_fft - hop,
                            window="hann", boundary="even",
                            padded=False, return_onesided=True)
        # scipy normalizes by window sum; compare shapes + peak bin
        assert out.shape[0] == n_fft // 2 + 1
        peak_ours = np.abs(out).mean(-1).argmax()
        peak_ref = np.abs(ref).mean(-1).argmax()
        assert abs(int(peak_ours) - int(peak_ref)) <= 1

    def test_stft_istft_roundtrip(self):
        rs2 = np.random.RandomState(0)
        x = rs2.randn(1, 2048).astype(np.float32)
        spec = paddle.signal.stft(paddle.to_tensor(x), n_fft=256,
                                  hop_length=64)
        back = paddle.signal.istft(spec, n_fft=256, hop_length=64,
                                   length=2048).numpy()
        np.testing.assert_allclose(back, x, rtol=1e-3, atol=1e-4)
