"""Auto-parallel Engine (reference auto_parallel/static/engine.py:98
via fleet.auto.Engine): planner-driven fit/evaluate/predict/cost on
the 8-virtual-device mesh."""
import numpy as np
import pytest

import jax
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.auto_parallel import Engine

rng = np.random.RandomState(4)


class _Data(paddle.io.Dataset):
    def __init__(self, n=64):
        self.x = rng.randn(n, 16).astype(np.float32)
        self.y = rng.randint(0, 4, (n,))

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _engine():
    paddle.seed(11)
    model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(),
                          nn.Linear(64, 4))
    opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=model.parameters())
    return Engine(model=model, loss=nn.CrossEntropyLoss(),
                  optimizer=opt)


def test_fleet_auto_namespace():
    assert fleet.auto.Engine is Engine
    assert hasattr(fleet.auto, "shard_tensor")
    assert hasattr(fleet.auto, "Planner")


def test_plan_and_cost():
    e = _engine()
    plans = e.plan(n_chips=8, global_batch=32)
    best = plans[0]
    assert best.dp * best.tp * best.pp == 8
    assert best.tp == 1 and best.pp == 1   # generic-layer family
    t, mem = e.cost(n_chips=8, global_batch=32)
    assert t > 0 and mem > 0


def test_fit_trains_with_dp_sharding():
    e = _engine()
    hist = e.fit(_Data(), epochs=2, batch_size=32)
    assert len(hist) == 2
    assert hist[1]["loss"] < hist[0]["loss"]
    assert e._plan.dp == len(jax.devices())   # batch sharded over all 8
    ev = e.evaluate(_Data(32), batch_size=32)
    assert np.isfinite(ev)
    outs = e.predict(_Data(32), batch_size=32)
    assert outs[0].shape == [32, 4]


def test_save_load_roundtrip(tmp_path):
    e = _engine()
    e.fit(_Data(), epochs=1, batch_size=32)
    path = str(tmp_path / "ckpt")
    e.save(path)
    e2 = _engine()
    e2.load(path)
    x = paddle.to_tensor(rng.randn(4, 16).astype(np.float32))
    np.testing.assert_allclose(e2.model(x).numpy(),
                               e.model(x).numpy(), rtol=1e-6)


def test_history_with_validation():
    e = _engine()
    hist = e.fit(_Data(), epochs=1, batch_size=32,
                 valid_data=_Data(32))
    assert "eval_loss" in hist[0] and np.isfinite(hist[0]["eval_loss"])
