"""Auto-parallel Engine (reference auto_parallel/static/engine.py:98
via fleet.auto.Engine): planner-driven fit/evaluate/predict/cost on
the 8-virtual-device mesh."""
import numpy as np
import pytest

import jax
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.auto_parallel import Engine

rng = np.random.RandomState(4)


class _Data(paddle.io.Dataset):
    def __init__(self, n=64):
        self.x = rng.randn(n, 16).astype(np.float32)
        self.y = rng.randint(0, 4, (n,))

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _engine():
    paddle.seed(11)
    model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(),
                          nn.Linear(64, 4))
    opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                 parameters=model.parameters())
    return Engine(model=model, loss=nn.CrossEntropyLoss(),
                  optimizer=opt)


def test_fleet_auto_namespace():
    assert fleet.auto.Engine is Engine
    assert hasattr(fleet.auto, "shard_tensor")
    assert hasattr(fleet.auto, "Planner")


def test_plan_and_cost():
    e = _engine()
    plans = e.plan(n_chips=8, global_batch=32)
    best = plans[0]
    assert best.dp * best.tp * best.pp == 8
    assert best.tp == 1 and best.pp == 1   # generic-layer family
    t, mem = e.cost(n_chips=8, global_batch=32)
    assert t > 0 and mem > 0


def test_fit_trains_with_dp_sharding():
    e = _engine()
    hist = e.fit(_Data(), epochs=2, batch_size=32)
    assert len(hist) == 2
    assert hist[1]["loss"] < hist[0]["loss"]
    assert e._plan.dp == len(jax.devices())   # batch sharded over all 8
    ev = e.evaluate(_Data(32), batch_size=32)
    assert np.isfinite(ev)
    outs = e.predict(_Data(32), batch_size=32)
    assert outs[0].shape == [32, 4]


def test_save_load_roundtrip(tmp_path):
    e = _engine()
    e.fit(_Data(), epochs=1, batch_size=32)
    path = str(tmp_path / "ckpt")
    e.save(path)
    e2 = _engine()
    e2.load(path)
    x = paddle.to_tensor(rng.randn(4, 16).astype(np.float32))
    np.testing.assert_allclose(e2.model(x).numpy(),
                               e.model(x).numpy(), rtol=1e-6)


def test_history_with_validation():
    e = _engine()
    hist = e.fit(_Data(), epochs=1, batch_size=32,
                 valid_data=_Data(32))
    assert "eval_loss" in hist[0] and np.isfinite(hist[0]["eval_loss"])


# ---------------------------------------------------------------------
# Round 3 (VERDICT r2 item 3): generic-model TP/PP through the Engine
# ---------------------------------------------------------------------
def _llama_pieces(seed=0):
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    paddle.seed(seed)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    ce = nn.CrossEntropyLoss()

    def loss_fn(logits, labels):
        return ce(logits[:, :-1].reshape([-1, logits.shape[-1]]),
                  labels[:, 1:].reshape([-1]))
    return m, loss_fn


def test_engine_tp_pp_on_stock_llama_loss_parity():
    """Engine.fit-style step with a tp=2/pp=2/dp=2 plan on an
    UNMODIFIED LlamaForCausalLM (no fleet layers): loss and updated
    params match a single-device run."""
    import jax
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.distributed.auto_parallel.engine import Engine
    from paddle_tpu.distributed.planner import PlanCandidate

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 256, (8, 16))

    # single-device oracle
    m0, loss_fn = _llama_pieces()
    opt0 = paddle.optimizer.SGD(0.05, parameters=m0.parameters())
    loss_ref = loss_fn(m0(paddle.to_tensor(ids)), paddle.to_tensor(ids))
    loss_ref.backward()
    opt0.step()
    opt0.clear_grad()

    m, loss_fn = _llama_pieces()            # same seed -> same init
    opt = paddle.optimizer.SGD(0.05, parameters=m.parameters())
    eng = Engine(model=m, loss=loss_fn, optimizer=opt)
    plan = PlanCandidate(dp=2, tp=2, pp=2, microbatches=4)
    eng.prepare(global_batch=8, plan=plan)
    with eng._mesh:
        loss = eng._step(eng._shard_batch(ids), eng._shard_batch(ids))

    np.testing.assert_allclose(float(loss._data), float(loss_ref),
                               rtol=2e-4)
    for (n0, p0), (n1, p1) in zip(m0.named_parameters(),
                                  m.named_parameters()):
        np.testing.assert_allclose(p1.numpy(), p0.numpy(), rtol=2e-3,
                                   atol=2e-5, err_msg=n0)


def test_engine_tp_only_on_arbitrary_mlp():
    """tp=2 auto-annotation on a model with NO block structure: params
    actually sharded over mp; training parity vs single device."""
    import jax
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed.auto_parallel.engine import Engine
    from paddle_tpu.distributed.planner import PlanCandidate

    def build():
        paddle.seed(3)
        return nn.Sequential(nn.Linear(16, 64), nn.GELU(),
                             nn.Linear(64, 8))

    rng = np.random.RandomState(1)
    x = rng.randn(8, 16).astype("float32")
    y = rng.randn(8, 8).astype("float32")
    mse = nn.MSELoss()

    m0 = build()
    opt0 = paddle.optimizer.SGD(0.1, parameters=m0.parameters())
    l_ref = mse(m0(paddle.to_tensor(x)), paddle.to_tensor(y))
    l_ref.backward()
    opt0.step()

    m = build()
    opt = paddle.optimizer.SGD(0.1, parameters=m.parameters())
    eng = Engine(model=m, loss=mse, optimizer=opt)
    eng.prepare(global_batch=8,
                plan=PlanCandidate(dp=2, tp=2, pp=1))
    # the annotation really sharded the big weights over mp
    w = m[0].weight._data
    assert not w.sharding.is_fully_replicated
    with eng._mesh:
        loss = eng._step(eng._shard_batch(x), eng._shard_batch(y))
    np.testing.assert_allclose(float(loss._data), float(l_ref),
                               rtol=1e-5)
    for (_n, p0), (_, p1) in zip(m0.named_parameters(),
                                  m.named_parameters()):
        np.testing.assert_allclose(p1.numpy(), p0.numpy(), rtol=1e-4,
                                   atol=1e-6)


def test_engine_plan_searches_full_family_for_block_models():
    from paddle_tpu.distributed.auto_parallel.engine import Engine
    import paddle_tpu as paddle
    m, loss_fn = _llama_pieces()
    eng = Engine(model=m, loss=loss_fn,
                 optimizer=paddle.optimizer.SGD(
                     0.1, parameters=m.parameters()))
    plans = eng.plan(n_chips=8, global_batch=8, top_k=8)
    assert plans, "planner returned no feasible plans"
    # block-structured model: the search space includes model-parallel
    # families, not just dp x zero
    assert any(p.tp > 1 or p.pp > 1 for p in plans) or \
        all(p.dp == 8 for p in plans)


def test_engine_pp_raises_clearly_without_blocks():
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed.auto_parallel.engine import Engine
    from paddle_tpu.distributed.planner import PlanCandidate
    m = nn.Linear(4, 4)
    eng = Engine(model=m, loss=nn.MSELoss(),
                 optimizer=paddle.optimizer.SGD(
                     0.1, parameters=m.parameters()))
    with pytest.raises(NotImplementedError, match="block chain"):
        eng.prepare(global_batch=4,
                    plan=PlanCandidate(dp=1, tp=1, pp=2))


class _TiedLlama(paddle.nn.Layer):
    """Llama variant whose LM head REUSES the embedding weight (the
    reference SharedLayerDesc / tied-embedding pattern,
    pp_layers.py:76): one Tensor is consumed by the prologue (lookup)
    AND the epilogue (logits matmul). Under the Engine's pp partition
    both uses sit outside the block ring, so the tied weight's gradient
    is the sum of the prologue-vjp and epilogue-head contributions."""

    def __init__(self, cfg):
        super().__init__()
        from paddle_tpu.models.llama import LlamaBlock
        self.embed_tokens = nn.Embedding(cfg.vocab_size,
                                         cfg.hidden_size)
        self.layers = nn.LayerList([LlamaBlock(cfg)
                                    for _ in range(cfg.num_layers)])
        self.norm = nn.RMSNorm(cfg.hidden_size, epsilon=cfg.rms_eps)

    def forward(self, input_ids):
        x = self.embed_tokens(input_ids)
        for blk in self.layers:
            x = blk(x, 0)
        x = self.norm(x)
        return paddle.matmul(x, self.embed_tokens.weight,
                             transpose_y=True)


def test_engine_pp_tied_embedding_loss_parity():
    """VERDICT r3 item 7: a tied-embedding llama trains tp2/pp2 via the
    Engine with loss/update parity against a single-device run — the
    SharedLayerDesc capability expressed through the partitioner's
    outside-the-ring prologue/epilogue."""
    from paddle_tpu.models.llama import LlamaConfig
    from paddle_tpu.distributed.auto_parallel.engine import Engine
    from paddle_tpu.distributed.planner import PlanCandidate

    cfg = LlamaConfig.tiny()
    ce = nn.CrossEntropyLoss()

    def loss_fn(logits, labels):
        return ce(logits[:, :-1].reshape([-1, logits.shape[-1]]),
                  labels[:, 1:].reshape([-1]))

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (8, 16))

    paddle.seed(7)
    m0 = _TiedLlama(cfg)
    opt0 = paddle.optimizer.SGD(0.05, parameters=m0.parameters())
    loss_ref = loss_fn(m0(paddle.to_tensor(ids)), paddle.to_tensor(ids))
    loss_ref.backward()
    opt0.step()
    opt0.clear_grad()

    paddle.seed(7)
    m = _TiedLlama(cfg)
    opt = paddle.optimizer.SGD(0.05, parameters=m.parameters())
    eng = Engine(model=m, loss=loss_fn, optimizer=opt)
    plan = PlanCandidate(dp=2, tp=2, pp=2, microbatches=4)
    eng.prepare(global_batch=8, plan=plan)
    with eng._mesh:
        loss = eng._step(eng._shard_batch(ids), eng._shard_batch(ids))

    np.testing.assert_allclose(float(loss._data), float(loss_ref),
                               rtol=2e-4)
    # the tied weight's update must carry BOTH gradient paths
    for (n0, p0), (n1, p1) in zip(m0.named_parameters(),
                                  m.named_parameters()):
        np.testing.assert_allclose(p1.numpy(), p0.numpy(), rtol=2e-3,
                                   atol=2e-5, err_msg=n0)


class _MaskBlock(paddle.nn.Layer):
    """Block taking (hidden, mask): the tuple-valued stage IO of the
    reference PipelineLayer (pp_layers.py:56)."""

    def __init__(self, h):
        super().__init__()
        self.fc = nn.Linear(h, h)

    def forward(self, x, mask):
        return paddle.tanh(self.fc(x)) * mask + x


class _MaskModel(paddle.nn.Layer):
    def __init__(self, h=16, n=4):
        super().__init__()
        self.embed = nn.Linear(8, h)
        self.blocks = nn.LayerList([_MaskBlock(h) for _ in range(n)])
        self.head = nn.Linear(h, 4)

    def forward(self, x):
        h = self.embed(x)
        # the mask derives from the INPUT inside the prologue — every
        # block consumes it as a per-microbatch side value
        mask = (x.mean(axis=-1, keepdim=True) > 0).astype("float32")
        for b in self.blocks:
            h = b(h, mask)
        return self.head(h)


def test_engine_pp_blocks_with_tuple_io():
    """VERDICT r3 item 7: blocks passing (hidden, mask) tuples train
    pp=2 through the Engine with parity against single-device."""
    from paddle_tpu.distributed.auto_parallel.engine import Engine
    from paddle_tpu.distributed.planner import PlanCandidate

    rng2 = np.random.RandomState(3)
    x = rng2.randn(8, 8).astype(np.float32)
    y = rng2.randint(0, 4, (8,))

    paddle.seed(9)
    m0 = _MaskModel()
    opt0 = paddle.optimizer.SGD(0.05, parameters=m0.parameters())
    ce = nn.CrossEntropyLoss()
    loss_ref = ce(m0(paddle.to_tensor(x)), paddle.to_tensor(y))
    loss_ref.backward()
    opt0.step()
    opt0.clear_grad()

    paddle.seed(9)
    m = _MaskModel()
    opt = paddle.optimizer.SGD(0.05, parameters=m.parameters())
    eng = Engine(model=m, loss=ce, optimizer=opt)
    plan = PlanCandidate(dp=1, tp=1, pp=2, microbatches=2)
    eng.prepare(global_batch=8, plan=plan)
    with eng._mesh:
        loss = eng._step(eng._shard_batch(x), eng._shard_batch(y))

    np.testing.assert_allclose(float(loss._data), float(loss_ref),
                               rtol=2e-4)
    for (n0, p0), (n1, p1) in zip(m0.named_parameters(),
                                  m.named_parameters()):
        np.testing.assert_allclose(p1.numpy(), p0.numpy(), rtol=2e-3,
                                   atol=2e-5, err_msg=n0)


def test_engine_zero_bubble_pp_loss_parity():
    """Engine.prepare(zero_bubble=True) at tp=1/pp=2 compiles the
    generic-model pipeline onto the ZBH1 dx/dW-split ring — loss and
    updated params match the single-device oracle exactly as the 1F1B
    engine does. With tp>1 the knob is ignored (1f1b)."""
    import jax
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.distributed.auto_parallel.engine import Engine
    from paddle_tpu.distributed.planner import PlanCandidate

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 256, (8, 16))

    m0, loss_fn = _llama_pieces()
    opt0 = paddle.optimizer.SGD(0.05, parameters=m0.parameters())
    loss_ref = loss_fn(m0(paddle.to_tensor(ids)), paddle.to_tensor(ids))
    loss_ref.backward()
    opt0.step()
    opt0.clear_grad()

    m, loss_fn = _llama_pieces()
    opt = paddle.optimizer.SGD(0.05, parameters=m.parameters())
    eng = Engine(model=m, loss=loss_fn, optimizer=opt)
    plan = PlanCandidate(dp=1, tp=1, pp=2, microbatches=4)
    eng.prepare(global_batch=8, plan=plan, zero_bubble=True)
    assert eng._partition.pp_schedule == "zbh1"
    with eng._mesh:
        loss = eng._step(eng._shard_batch(ids), eng._shard_batch(ids))

    np.testing.assert_allclose(float(loss._data), float(loss_ref),
                               rtol=2e-4)
    for (n0, p0), (n1, p1) in zip(m0.named_parameters(),
                                  m.named_parameters()):
        np.testing.assert_allclose(p1.numpy(), p0.numpy(), rtol=2e-3,
                                   atol=2e-5, err_msg=n0)

    # tp>1 plans fall back to 1f1b rather than refusing
    m2, loss_fn2 = _llama_pieces()
    opt2 = paddle.optimizer.SGD(0.05, parameters=m2.parameters())
    eng2 = Engine(model=m2, loss=loss_fn2, optimizer=opt2)
    eng2.prepare(global_batch=8,
                 plan=PlanCandidate(dp=1, tp=2, pp=2, microbatches=4),
                 zero_bubble=True)
    assert eng2._partition.pp_schedule == "1f1b"


def test_engine_zbvpp_pp_loss_parity():
    """Engine.prepare(zero_bubble="zbvpp") at tp=1/pp=2 on a 4-layer
    llama: the partitioner V-gathers the block chain into [pp, 2, Lc]
    virtual chunks, trains on the compiled ZB-V ring, and the inverse
    gather writes grads back to the right layers — loss and updated
    params match the single-device oracle."""
    import jax
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.distributed.auto_parallel.engine import Engine
    from paddle_tpu.distributed.planner import PlanCandidate

    cfg4 = LlamaConfig(vocab_size=256, hidden_size=64,
                       intermediate_size=128, num_layers=4,
                       num_heads=4, num_kv_heads=2, max_seq_len=64)
    ce = nn.CrossEntropyLoss()

    def loss_fn(logits, labels):
        return ce(logits[:, :-1].reshape([-1, logits.shape[-1]]),
                  labels[:, 1:].reshape([-1]))

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 256, (8, 16))

    paddle.seed(0)
    m0 = LlamaForCausalLM(cfg4)
    opt0 = paddle.optimizer.SGD(0.05, parameters=m0.parameters())
    loss_ref = loss_fn(m0(paddle.to_tensor(ids)), paddle.to_tensor(ids))
    loss_ref.backward()
    opt0.step()
    opt0.clear_grad()

    paddle.seed(0)
    m = LlamaForCausalLM(cfg4)
    opt = paddle.optimizer.SGD(0.05, parameters=m.parameters())
    eng = Engine(model=m, loss=loss_fn, optimizer=opt)
    plan = PlanCandidate(dp=1, tp=1, pp=2, microbatches=4)
    eng.prepare(global_batch=8, plan=plan, zero_bubble="zbvpp")
    assert eng._partition.pp_schedule == "zbvpp"
    with eng._mesh:
        loss = eng._step(eng._shard_batch(ids), eng._shard_batch(ids))

    np.testing.assert_allclose(float(loss._data), float(loss_ref),
                               rtol=2e-4)
    for (n0, p0), (n1, p1) in zip(m0.named_parameters(),
                                  m.named_parameters()):
        np.testing.assert_allclose(p1.numpy(), p0.numpy(), rtol=2e-3,
                                   atol=2e-5, err_msg=n0)
