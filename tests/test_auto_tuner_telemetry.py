"""Telemetry-driven auto-tuning (ISSUE 13 tentpole): tune() scores
candidates from metrics-registry deltas — no caller wall clock — plus
the JSONL trial log's warm-start contract and the planner-refusal
pruning. Everything here is pure host Python over the registry: no
jax arrays, no devices, sub-second."""
import json

import pytest

import paddle_tpu.observability as obs
from paddle_tpu.distributed.auto_tuner import (
    Candidate, default_score, generate_candidates, prune_by_planner,
    tune)


@pytest.fixture(autouse=True)
def _metrics_on():
    obs.enable()
    yield
    obs.enable()


def _emit_steps(n_steps, step_s, tokens_per_step, mfu=None,
                compiles=0):
    """Simulate what the instrumented train loop writes per candidate
    run (observability.training.record_step + the compile hook)."""
    r = obs.REGISTRY
    for _ in range(n_steps):
        r.counter("train.steps").inc()
        r.histogram("train.step_time_s").observe(step_s)
        r.counter("train.tokens").inc(tokens_per_step)
    if mfu is not None:
        r.gauge("train.mfu").set(mfu)
    if compiles:
        r.counter("jit.xla_compiles").inc(compiles)


# candidate key -> (step_s, mfu): dp4 is the clear winner
PROFILES = {
    (4, 1, 1): (0.10, 0.60),
    (2, 1, 2): (0.15, 0.40),
    (1, 1, 4): (0.30, 0.20),
    (2, 2, 1): (0.20, 0.30),
    (1, 2, 2): (0.25, 0.25),
    (1, 4, 1): (0.40, 0.10),
}


def _run_candidate(c):
    """Executes a fake candidate: moves the registry, returns None —
    tune() must derive everything from the snapshot delta."""
    step_s, mfu = PROFILES[(c.dp, c.pp, c.tp)]
    _emit_steps(3, step_s, tokens_per_step=1024, mfu=mfu, compiles=2)
    return None


def _cands():
    return [Candidate(dp=dp, pp=pp, tp=tp)
            for (dp, pp, tp) in PROFILES]


def test_tune_selects_best_from_registry_scores():
    best = tune(_run_candidate, _cands(), verbose=False)
    assert (best.dp, best.pp, best.tp) == (4, 1, 1)
    # the score came from the registry, not a wall clock: the window's
    # mfu gauge and tokens-per-step-second are recorded per candidate
    assert best.score == pytest.approx(0.60)       # mfu primary signal
    m = best.measurements
    assert m["steps"] == 3
    assert m["mean_step_s"] == pytest.approx(0.10)
    assert m["tokens_per_s"] == pytest.approx(1024 / 0.10)
    assert m["compiles"] == 2.0


def test_recompile_penalty_orders_candidates():
    # same MFU, but one config recompiles every step -> must lose
    assert default_score({"mfu": 0.5, "compiles": 2}) > \
        default_score({"mfu": 0.5, "compiles": 12})
    # ladder: no mfu -> tokens/s; neither -> 1/step-time
    assert default_score({"tokens_per_s": 100.0, "compiles": 0}) == \
        pytest.approx(100.0)
    assert default_score({"mean_step_s": 0.25}) == pytest.approx(4.0)
    assert default_score({}) == 0.0


def test_uniform_signal_rescoring_never_mixes_scales():
    """Two candidates with IDENTICAL achieved MFU: the second one's
    gauge write is invisible (value unchanged), so per-candidate
    fallback would score it on tokens/s (thousands) against the
    first's mfu (0..1) and hand it the win on a scale artifact. The
    uniform rescoring drops mfu for BOTH and ranks on tokens/s."""
    obs.REGISTRY.gauge("train.mfu").set(0.123)   # known pre-state
    cands = [Candidate(dp=4, pp=1, tp=1),        # fast: 0.1 s/step
             Candidate(dp=1, pp=1, tp=4)]        # slow: 0.4 s/step

    def run_fn(c):
        _emit_steps(2, 0.1 if c.dp == 4 else 0.4, 1024, mfu=0.45)

    best = tune(run_fn, cands, verbose=False)
    assert (best.dp, best.tp) == (4, 1)
    # both candidates ended on the SAME signal (registry tokens/s)
    assert cands[0].score == pytest.approx(1024 / 0.1)
    assert cands[1].score == pytest.approx(1024 / 0.4)


def test_trial_log_warm_start_skips_completed(tmp_path):
    trials = str(tmp_path / "trials.jsonl")
    runs = []

    def run_fn(c):
        runs.append(c.key)
        if c.pp == 4:
            raise RuntimeError("oom")        # failures are logged too
        _emit_steps(2, 0.1 * c.tp + 0.05 * c.pp, 512)
        return None

    best1 = tune(run_fn, _cands(), verbose=False, trials_path=trials)
    n_first = len(runs)
    assert n_first == len(PROFILES)
    recs = [json.loads(ln) for ln in open(trials)]
    assert len(recs) == len(PROFILES)
    assert any(r["error"] for r in recs)           # the oom trial
    assert all("key" in r for r in recs)

    # second run: every candidate (including the failed one) is
    # satisfied from the log — run_fn never fires again
    skipped0 = obs.counter("autotuner.trials_skipped").value
    best2 = tune(run_fn, _cands(), verbose=False, trials_path=trials)
    assert len(runs) == n_first
    assert (best2.dp, best2.pp, best2.tp) == (best1.dp, best1.pp,
                                              best1.tp)
    assert best2.score == pytest.approx(best1.score)
    assert obs.counter("autotuner.trials_skipped").value >= \
        skipped0 + len(PROFILES)
    # nothing new appended
    assert len([json.loads(ln) for ln in open(trials)]) == len(PROFILES)

    # a NEW candidate extends the log instead of restarting it
    extra = Candidate(dp=8, pp=1, tp=1)
    tune(run_fn, _cands() + [extra], verbose=False,
         trials_path=trials)
    assert len(runs) == n_first + 1 and runs[-1] == extra.key
    assert len([json.loads(ln) for ln in open(trials)]) == \
        len(PROFILES) + 1


def test_trial_log_corrupt_tail_does_not_poison(tmp_path):
    trials = tmp_path / "trials.jsonl"
    trials.write_text(json.dumps(
        {"key": "dp4_pp1_tp1_mb1_sp0_z0_r1", "score": 1e6}) +
        "\n{truncated")
    ran = []

    def run_fn(c):
        ran.append(c.key)
        _emit_steps(1, 0.2, 128)

    best = tune(run_fn, _cands(), verbose=False,
                trials_path=str(trials))
    # the intact line warm-starts (and wins with its recorded score);
    # the corrupt tail is ignored, remaining candidates still run
    assert best.key == "dp4_pp1_tp1_mb1_sp0_z0_r1"
    assert best.score == pytest.approx(1e6)
    assert len(ran) == len(PROFILES) - 1


def test_pinned_source_never_reuses_other_mode_trials(tmp_path):
    """Wallclock scores (1/s) and telemetry scores (mfu / tokens/s)
    live on incomparable scales — a pinned-source sweep re-measures
    rather than warm-starting from the other mode's log."""
    trials = str(tmp_path / "t.jsonl")
    tune(lambda c: 0.2 / c.dp, _cands(), verbose=False,
         trials_path=trials, source="wallclock")
    ran = []

    def tele_run(c):
        ran.append(c.key)
        _emit_steps(1, 0.1, 256)

    tune(tele_run, _cands(), verbose=False, trials_path=trials,
         source="telemetry")
    assert len(ran) == len(PROFILES)   # nothing reused across modes
    # same mode: the telemetry records now warm-start (newest wins is
    # not needed — _load_trials keeps the LAST record per key)
    tune(tele_run, _cands(), verbose=False, trials_path=trials,
         source="telemetry")
    assert len(ran) == len(PROFILES)


def test_mixed_mode_run_fn_aborts_loudly(tmp_path):
    """A run_fn that switches scoring modes mid-sweep is a caller bug:
    tune() ABORTS (either direction) instead of silently dropping the
    mismatched candidates and crowning a winner from the survivors,
    and no trial is logged for the mismatch."""
    trials = str(tmp_path / "t.jsonl")
    calls = []

    def wall_then_tele(c):
        calls.append(c)
        if len(calls) == 1:
            return 0.25            # resolves the sweep to wallclock
        return None                # then switches mode

    cands = [Candidate(dp=4, pp=1, tp=1), Candidate(dp=1, pp=1, tp=4)]
    with pytest.raises(RuntimeError, match="mix scoring modes"):
        tune(wall_then_tele, cands, verbose=False, trials_path=trials)
    # only the clean first trial was persisted
    assert len(open(trials).read().splitlines()) == 1

    def tele_then_wall(c):
        calls.append(c)
        _emit_steps(1, 0.1, 64)
        return 0.25 if len(calls) >= 4 else None

    calls.clear()
    with pytest.raises(RuntimeError, match="mix scoring modes"):
        tune(tele_then_wall,
             [Candidate(dp=4, pp=1, tp=1), Candidate(dp=2, pp=1, tp=2),
              Candidate(dp=1, pp=1, tp=4), Candidate(dp=1, pp=2, tp=2)],
             verbose=False)


def test_wallclock_mode_backward_compatible():
    cands = generate_candidates(8, num_layers=4, global_batch=16,
                                num_heads=8)

    def fake_run(c):
        if c.tp == 8:
            raise RuntimeError("oom")
        return 1.0 / (c.dp + 0.5 * c.tp)

    best = tune(fake_run, cands, verbose=False)
    assert best.error is None and best.time_s is not None
    # fastest feasible = max(dp + 0.5*tp) = dp8/tp1 -> 1/8.5 s
    assert best.time_s == pytest.approx(1.0 / 8.5)
    assert best.score == pytest.approx(8.5)


def test_prune_by_planner_refuses_and_annotates():
    from paddle_tpu.distributed.planner import ModelSpec
    spec = ModelSpec.gpt(n_params=350e6, layers=24, hidden=1024,
                         heads=16, seq=1024, vocab=50304)
    cands = [Candidate(dp=4, pp=1, tp=1),          # fine
             Candidate(dp=1, pp=1, tp=4),          # fine (16 % 4 == 0)
             Candidate(dp=1, pp=7, tp=1, microbatches=8),  # 24 % 7
             Candidate(dp=2, pp=1, tp=1),          # mesh mismatch (2 != 4)
             Candidate(dp=1, pp=2, tp=2, microbatches=1),  # mb < pp
             Candidate(dp=1, pp=1, tp=4, zero=2)]  # zero needs dp>1
    kept = prune_by_planner(cands, spec, n_chips=4, global_batch=8)
    kept_keys = {(c.dp, c.pp, c.tp) for c in kept}
    assert kept_keys == {(4, 1, 1), (1, 1, 4)}
    refused = [c for c in cands if c not in kept]
    assert all(c.error and c.error.startswith("planner_refused")
               for c in refused)
    # survivors carry the planner's estimate for inspection
    assert all(c.plan is not None and c.plan.est_step_s > 0
               for c in kept)
    # and tune(planner_spec=...) composes: refused configs never run
    seen = []

    def run_fn(c):
        seen.append((c.dp, c.pp, c.tp))
        return 1.0 / c.dp
    best = tune(run_fn, list(cands), verbose=False,
                planner_spec=(spec, 4, 8))
    assert set(seen) == kept_keys
    assert (best.dp, best.pp, best.tp) == (4, 1, 1)


def test_planner_rules_lockstep():
    """Planner.refusal_reason is the single home of the structural
    legality rules: every config candidates() enumerates must pass it,
    so the tuner's pruning can never drift from the planner's own
    search space."""
    from paddle_tpu.distributed.planner import ModelSpec, Planner
    spec = ModelSpec.gpt(n_params=350e6, layers=24, hidden=1024,
                         heads=16, seq=1024, vocab=50304)
    pl = Planner("v5e")
    cands = pl.candidates(spec, n_chips=8, global_batch=16)
    assert cands
    for p in cands:
        reason = pl.refusal_reason(
            spec, 8, 16, dp=p.dp, tp=p.tp, pp=p.pp,
            microbatches=p.microbatches, zero=p.zero)
        assert reason is None, (
            f"candidates() proposed a config refusal_reason rejects "
            f"({reason}): dp={p.dp} tp={p.tp} pp={p.pp} "
            f"mb={p.microbatches} zero={p.zero} — the two rule sets "
            "have drifted")
