"""Autograd engine tests (reference semantics: eager/backward.cc RunBackward,
grad accumulation, hooks, paddle.grad, PyLayer)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_simple_backward():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 4, 6])


def test_chain():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * x
    z = y * x  # x^3 -> 3x^2 = 12
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), 12.0)


def test_grad_accumulation():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    (x * 2).backward()
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([2.0], stop_gradient=True)
    z = (x * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_detach_cuts_graph():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * 2).detach()
    z = y * 3
    assert z.stop_gradient


def test_multi_output_op_grad():
    x = paddle.to_tensor([[3.0, 1.0], [2.0, 4.0]], stop_gradient=False)
    vals, idx = paddle.topk(x, k=1, axis=1)
    vals.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1, 0], [0, 1]])


def test_branching_graph():
    x = paddle.to_tensor(3.0, stop_gradient=False)
    a = x * 2
    b = x * 4
    (a + b).backward()
    np.testing.assert_allclose(x.grad.numpy(), 6.0)


def test_backward_with_grad_tensor():
    x = paddle.to_tensor([1.0, 1.0], stop_gradient=False)
    y = x * 2
    y.backward(paddle.to_tensor([1.0, 3.0]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 6.0])


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient


def test_paddle_grad():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x
    (g,) = paddle.grad(y, x)
    np.testing.assert_allclose(g.numpy(), [4.0])
    assert x.grad is None  # grad() must not touch .grad


def test_paddle_grad_intermediate():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x
    z = y * 3
    (gy,) = paddle.grad(z, y)
    np.testing.assert_allclose(gy.numpy(), [3.0])


def test_register_hook():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    seen = []
    x.register_hook(lambda g: seen.append(g.numpy()))
    (x * 5).backward()
    assert len(seen) == 1
    np.testing.assert_allclose(seen[0], [5.0])


def test_hook_modifies_grad():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    x.register_hook(lambda g: g * 2)
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_retain_graph():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0])


def test_double_backward_raises_without_retain():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_pylayer():
    class Double(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, dy):
            return dy * 2

    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = Double.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_finite_difference_matmul():
    rng = np.random.RandomState(0)
    a = rng.randn(3, 4).astype(np.float32)
    b = rng.randn(4, 2).astype(np.float32)
    x = paddle.to_tensor(a, stop_gradient=False)
    w = paddle.to_tensor(b, stop_gradient=False)
    paddle.matmul(x, w).sum().backward()
    # analytic: dL/dx = ones @ b.T
    np.testing.assert_allclose(x.grad.numpy(),
                               np.ones((3, 2)) @ b.T, rtol=1e-5)
    np.testing.assert_allclose(w.grad.numpy(),
                               a.T @ np.ones((3, 2)), rtol=1e-5)


def test_jacobian():
    x = paddle.to_tensor([1.0, 2.0])
    jac = paddle.autograd.jacobian(lambda t: t * t, x)
    np.testing.assert_allclose(jac.numpy(), np.diag([2.0, 4.0]))
