"""q×kv-blocked flash attention (ops/pallas/blocked_flash.py).

Parity vs plain XLA attention — fwd and grads, causal and non-causal,
block-divisible and ragged sequence lengths — all in interpret mode so
the exact TPU kernel code runs on the CPU tier. Shapes are kept small:
the whole module must stay well under the ~15 s tier-1 budget.
"""
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import blocked_flash as bf


def _xla_ref(q, k, v, causal, scale=None):
    """Plain XLA attention in the kernel's [B,H,S,D] layout, f32."""
    d = q.shape[-1]
    sm = scale if scale is not None else 1.0 / math.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        iq = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        ik = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where((iq >= ik)[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _qkvw(b, h, sq, skv, d, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda s: jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))
    return mk(sq), mk(skv), mk(skv), jnp.asarray(
        rng.randn(b, h, sq, d).astype(np.float32))


def _assert_parity(q, k, v, w, causal, bq, bkv, grad=True,
                   rtol=2e-4, atol=2e-4):
    out = bf.attention_bhsd(q, k, v, causal=causal, interpret=True,
                            block_q=bq, block_kv=bkv)
    ref = _xla_ref(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=rtol, atol=atol)
    if not grad:
        return

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) * w)

    g = jax.grad(loss(lambda q, k, v: bf.attention_bhsd(
        q, k, v, causal=causal, interpret=True,
        block_q=bq, block_kv=bkv)), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(lambda q, k, v: _xla_ref(q, k, v, causal)),
                  argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g, gr, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=rtol, atol=atol, err_msg=name)


@pytest.mark.parametrize("causal", [True, False])
def test_parity_multiblock(causal):
    # 2 q-blocks x 2 kv-blocks: exercises init/accumulate/finalize and
    # (causal) the diagonal-straddle mask plus one fully-skipped tile
    q, k, v, w = _qkvw(1, 2, 256, 256, 64)
    _assert_parity(q, k, v, w, causal, 128, 128)


def test_parity_unequal_blocks_causal():
    # bq != bkv: the diagonal crosses kv tiles mid-block, so last_ki /
    # straddle-detection logic differs from the square-block case
    # fwd-only: the bwd kernels' block geometry is already covered by
    # the grad checks above; a second causal grad trace would double
    # the module's interpret-mode tracing bill (~15 s budget)
    q, k, v, w = _qkvw(1, 1, 512, 512, 64, seed=1)
    _assert_parity(q, k, v, w, True, 128, 256, grad=False)


def test_parity_ragged_autoblocks():
    # S=384 is a multiple of 128 but of no preferred block: the picker
    # must fall back to 128 and stay exact
    assert bf._blocks_for(384, 384) == (128, 128)
    q, k, v, w = _qkvw(1, 1, 384, 384, 64, seed=2)
    _assert_parity(q, k, v, w, True, None, None, grad=False)


def test_parity_cross_attention():
    # S != Skv (non-causal): kv-block count differs from q-block count
    q, k, v, w = _qkvw(1, 1, 256, 384, 64, seed=3)
    _assert_parity(q, k, v, w, False, 128, 128, grad=False)


def test_shape_gate():
    # D not a lane multiple, ragged-by-128 seqs, causal cross-attn:
    # all rejected; the long-S shape the dispatch chain routes here is
    # accepted (no VMEM-derived S-cap)
    assert bf.supported((2, 8, 4096, 128), 4096, jnp.bfloat16, True)
    assert bf.supported((2, 8, 16384, 128), 16384, jnp.bfloat16, True)
    assert not bf.supported((2, 8, 512, 80), 512, jnp.bfloat16, True)
    assert not bf.supported((2, 8, 320, 128), 320, jnp.bfloat16, True)
    assert not bf.supported((2, 8, 512, 128), 1024, jnp.bfloat16, True)
    assert bf.supported((2, 8, 512, 128), 1024, jnp.bfloat16, False)
    assert not bf.supported((2, 8, 512, 128), 512, jnp.int8, True)


def test_block_candidates():
    # divisibility-filtered, preferred-first; ragged falls back to the
    # auto-picked pair so the autotuner always has >= 1 blocked variant
    assert bf.block_candidates(4096, 4096) == [
        (512, 512), (256, 512), (512, 1024)]
    assert bf.block_candidates(640, 640) == [(128, 128)]


def test_explicit_block_must_divide():
    q, k, v, _ = _qkvw(1, 1, 256, 256, 64)
    with pytest.raises(ValueError):
        bf.attention_bhsd(q, k, v, causal=True, interpret=True,
                          block_q=192, block_kv=128)


def test_dispatch_fallback_counted_not_raised(monkeypatch):
    """Ride-along fix: a head dim that is not a multiple of the lane
    width must route to plain XLA attention (return None) and tick the
    attn.dispatch_fallback counter — never raise."""
    import paddle_tpu.observability as obs
    from paddle_tpu.ops.pallas import flash_attention as fa

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")

    def delta(reason, q, k):
        c = obs.REGISTRY.counter("attn.dispatch_fallback",
                                 reason=reason)
        before = c.value
        assert fa.flash_attention_maybe(q, k, k, causal=True) is None
        return c.value - before

    q = jnp.zeros((1, 128, 2, 80), jnp.float32)     # D=80: 80 % 64 != 0
    assert delta("head_dim", q, q) == 1.0
    q = jnp.zeros((1, 100, 2, 64), jnp.float32)     # ragged seq
    assert delta("seq_len", q, q) == 1.0
