"""Causal-skip monolithic kernel (ops/pallas/causal_attention.py)
numerics in interpret mode. The kernel is correct but measured slower
e2e than simple_attention at S=1024 on v5e (see its docstring) — it is
an available op, not in the flash dispatch chain."""
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.causal_attention import (attention_bhsd,
                                                    supported, _NQ)

B, H, S, D = 2, 2, 256, 128


def naive(q, k, v):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(D)
    mask = jnp.tril(jnp.ones((S, S), bool))
    s = jnp.where(mask, s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)


@pytest.fixture(scope="module")
def qkv():
    key = jax.random.PRNGKey(0)
    mk = lambda i: jax.random.normal(jax.random.fold_in(key, i),
                                     (B, H, S, D), jnp.float32) * 0.3
    return mk(0), mk(1), mk(2)


def test_forward_matches_naive(qkv):
    q, k, v = qkv
    out = attention_bhsd(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(naive(q, k, v)),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("argi", [0, 1, 2])
def test_grads_match_naive(qkv, argi):
    q, k, v = qkv
    args = [q, k, v]

    def fp(t):
        a = list(args)
        a[argi] = t
        return attention_bhsd(*a, causal=True, interpret=True).sum()

    def fn(t):
        a = list(args)
        a[argi] = t
        return naive(*a).sum()

    np.testing.assert_allclose(np.asarray(jax.grad(fp)(args[argi])),
                               np.asarray(jax.grad(fn)(args[argi])),
                               rtol=2e-3, atol=2e-4)


def test_supported_gate():
    assert supported((8, 8, 1024, 128), jnp.bfloat16)
    assert not supported((8, 8, 4096, 128), jnp.bfloat16)   # VMEM
    assert not supported((8, 8, 1024 + 128, 128), jnp.bfloat16) \
        or (1024 + 128) % (_NQ * 128) == 0
    assert not supported((8, 8, 1000, 128), jnp.bfloat16)   # tiling
