"""Causal-skip monolithic kernel (ops/pallas/causal_attention.py)
numerics in interpret mode. Slower than simple_attention at S=1024 on
v5e but ~1.8x faster than the q-block kernel at S=2048, so
flash_attention_maybe dispatches simple -> causal-skip -> q-block ->
library flash (see ops/pallas/flash_attention.py)."""
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.causal_attention import (attention_bhsd,
                                                    supported, _NQ)

B, H, S, D = 2, 2, 256, 128


def naive(q, k, v):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(D)
    mask = jnp.tril(jnp.ones((S, S), bool))
    s = jnp.where(mask, s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)


@pytest.fixture(scope="module")
def qkv():
    key = jax.random.PRNGKey(0)
    mk = lambda i: jax.random.normal(jax.random.fold_in(key, i),
                                     (B, H, S, D), jnp.float32) * 0.3
    return mk(0), mk(1), mk(2)


def test_forward_matches_naive(qkv):
    q, k, v = qkv
    out = attention_bhsd(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(naive(q, k, v)),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("argi", [0, 1, 2])
def test_grads_match_naive(qkv, argi):
    q, k, v = qkv
    args = [q, k, v]

    def fp(t):
        a = list(args)
        a[argi] = t
        return attention_bhsd(*a, causal=True, interpret=True).sum()

    def fn(t):
        a = list(args)
        a[argi] = t
        return naive(*a).sum()

    np.testing.assert_allclose(np.asarray(jax.grad(fp)(args[argi])),
                               np.asarray(jax.grad(fn)(args[argi])),
                               rtol=2e-3, atol=2e-4)


def test_supported_gate():
    assert supported((8, 8, 1024, 128), jnp.bfloat16)
    assert not supported((8, 8, 4096, 128), jnp.bfloat16)   # VMEM
    assert not supported((8, 8, 1024 + 128, 128), jnp.bfloat16) \
        or (1024 + 128) % (_NQ * 128) == 0
    assert not supported((8, 8, 1000, 128), jnp.bfloat16)   # tiling


def test_nq_adapts_to_seq_len():
    from paddle_tpu.ops.pallas.causal_attention import _pick_nq
    assert _pick_nq(1024, 128, 2) == 2      # widest strips fit
    assert _pick_nq(2048, 128, 2) == 8      # strips shrink to fit VMEM
    assert _pick_nq(4096, 128, 2) is None   # cannot fit -> unsupported


def test_s2048_matches_naive_interpret():
    S2 = 512  # interpret-mode proxy for the multi-nq path (nq from cap)
    import paddle_tpu.ops.pallas.causal_attention as ca_mod
    key = jax.random.PRNGKey(2)
    mk = lambda i: jax.random.normal(jax.random.fold_in(key, i),
                                     (1, 1, S2, 128), jnp.float32) * 0.3
    q, k, v = mk(0), mk(1), mk(2)
    # force nq=4 by shrinking the VMEM budget seen by _pick_nq
    orig = ca_mod._pick_nq
    ca_mod._pick_nq = lambda s, d, i, vmem_budget=0: 4
    try:
        out = attention_bhsd(q, k, v, causal=True, interpret=True)
    finally:
        ca_mod._pick_nq = orig
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(128)
    mask = jnp.tril(jnp.ones((S2, S2), bool))
    s = jnp.where(mask, s, -1e30)
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_hybrid_fwd_simple_bwd_parity():
    """Round-4 hybrid (strip forward + monolithic backward, residuals
    (q,k,v) only): outputs and grads match the reference einsum
    attention in interpret mode."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.causal_attention import (
        attention_bhsd_hybrid)

    b, h, s, d = 2, 2, 256, 64
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))

    def ref(q, k, v):
        sc = 1.0 / np.sqrt(d)
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sc
        iq = jax.lax.broadcasted_iota(jnp.int32, (s, s), 0)
        ik = jax.lax.broadcasted_iota(jnp.int32, (s, s), 1)
        logits = jnp.where((iq >= ik)[None, None], logits, -1e30)
        return jnp.einsum("bhqk,bhkd->bhqd",
                          jax.nn.softmax(logits, -1), v)

    out = attention_bhsd_hybrid(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref(q, k, v)),
                               rtol=2e-5, atol=2e-5)

    def loss_h(args):
        return jnp.sum(attention_bhsd_hybrid(*args, causal=True,
                                             interpret=True) ** 2)

    def loss_r(args):
        return jnp.sum(ref(*args) ** 2)

    gh = jax.grad(loss_h)((q, k, v))
    gr = jax.grad(loss_r)((q, k, v))
    for a, b_ in zip(gh, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)
