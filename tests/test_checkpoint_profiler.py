"""Distributed checkpoint (sharded save/re-shard load) + profiler tests."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


def test_sharded_checkpoint_roundtrip(tmp_path):
    from paddle_tpu.distributed.checkpoint import (load_state_dict,
                                                   save_state_dict)
    mesh = dist.ProcessMesh(shape=[8], dim_names=["dp"])
    w = dist.shard_tensor(
        paddle.to_tensor(np.arange(64, dtype=np.float32).reshape(8, 8)),
        mesh, [dist.Shard(0)])
    b = paddle.to_tensor(np.ones(8, np.float32))
    sd = {"w": w, "b": b, "step": 7}
    save_state_dict(sd, str(tmp_path / "ckpt"))
    assert os.path.exists(tmp_path / "ckpt" / "metadata.json")

    # load into a DIFFERENT sharding (re-shard on load)
    mesh2 = dist.ProcessMesh(shape=[4], dim_names=["x"])
    w2 = dist.shard_tensor(paddle.zeros([8, 8]), mesh2, [dist.Shard(1)])
    b2 = paddle.zeros([8])
    sd2 = {"w": w2, "b": b2, "step": 0}
    load_state_dict(sd2, str(tmp_path / "ckpt"))
    np.testing.assert_allclose(w2.numpy(),
                               np.arange(64).reshape(8, 8))
    np.testing.assert_allclose(b2.numpy(), np.ones(8))
    # target sharding preserved
    assert not w2._data.sharding.is_fully_replicated


def test_async_checkpoint(tmp_path):
    from paddle_tpu.distributed.checkpoint import (load_state_dict,
                                                   save_state_dict)
    sd = {"a": paddle.to_tensor([1.0, 2.0])}
    th = save_state_dict(sd, str(tmp_path / "ck2"), async_save=True)
    th.join()
    out = {"a": paddle.zeros([2])}
    load_state_dict(out, str(tmp_path / "ck2"))
    np.testing.assert_allclose(out["a"].numpy(), [1, 2])


def test_commit_marker_orders_the_save(tmp_path):
    """ISSUE 14 satellite: save publishes `_COMMITTED.json` LAST;
    load refuses a checkpoint without it (a save interrupted between
    shard and metadata writes is indistinguishable from a valid one
    by per-file inspection) unless require_committed=False."""
    from paddle_tpu.distributed import checkpoint as dc
    ck = tmp_path / "ck"
    sd = {"a": paddle.to_tensor([3.0, 4.0]), "step": 5}
    dc.save_state_dict(sd, str(ck))
    assert (ck / dc.COMMIT_MARKER).exists()
    assert dc.is_committed(str(ck))

    # an uncommitted (interrupted) save is refused with a clear error
    os.remove(ck / dc.COMMIT_MARKER)
    out = {"a": paddle.zeros([2]), "step": 0}
    with pytest.raises(ValueError, match="not committed"):
        dc.load_state_dict(out, str(ck))
    # legacy escape hatch still loads it
    dc.load_state_dict(out, str(ck), require_committed=False)
    np.testing.assert_allclose(out["a"].numpy(), [3, 4])

    # a TORN save (marker present, referenced shard missing) is
    # refused too — this is the read-side ordering guarantee
    dc.save_state_dict(sd, str(ck))
    os.remove(ck / "shard_0.npz")
    assert not dc.is_committed(str(ck))
    with pytest.raises(ValueError, match="partial"):
        dc.load_state_dict(out, str(ck))


def test_latest_committed_skips_in_progress_saves(tmp_path):
    """Elastic resume picks the NEWEST committed per-step directory,
    ignoring a newer save that never committed (killed mid-write)."""
    from paddle_tpu.distributed import checkpoint as dc
    root = tmp_path / "ckpts"
    root.mkdir()
    assert dc.latest_committed(str(root)) is None
    for step in (0, 1, 2):
        dc.save_state_dict({"a": paddle.to_tensor([float(step)]),
                            "step": step},
                           str(root / f"step_{step:04d}"))
    # step 3 "crashed" after writing its shard but before the marker
    dc.save_state_dict({"a": paddle.to_tensor([3.0]), "step": 3},
                       str(root / "step_0003"))
    os.remove(root / "step_0003" / dc.COMMIT_MARKER)
    latest = dc.latest_committed(str(root))
    assert latest is not None and latest.endswith("step_0002"), latest
    out = {"a": paddle.zeros([1]), "step": -1}
    dc.load_state_dict(out, latest)
    assert out["step"] == 2
    # a root that is itself a committed checkpoint returns itself
    dc.save_state_dict({"a": paddle.to_tensor([9.0])}, str(root))
    assert dc.latest_committed(str(root)) == str(root)


def test_profiler_spans_and_export(tmp_path):
    import paddle_tpu.profiler as profiler
    p = profiler.Profiler(
        on_trace_ready=profiler.export_chrome_tracing(str(tmp_path)))
    p.start()
    with profiler.RecordEvent("my_region"):
        x = paddle.randn([32, 32])
        (x @ x).sum().numpy()
    p.step(num_samples=32)
    p.stop()
    files = os.listdir(tmp_path)
    assert any(f.endswith(".json") for f in files)
    import json
    with open(tmp_path / [f for f in files if f.endswith(".json")][0]) as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"]}
    assert "my_region" in names
    assert any(n.startswith("op::") for n in names)
    assert "avg_step" in p.step_info()


def test_profiler_scheduler():
    import paddle_tpu.profiler as profiler
    sched = profiler.make_scheduler(closed=1, ready=1, record=2,
                                    repeat=1)
    states = [sched(i) for i in range(4)]
    assert states[0] == profiler.ProfilerState.CLOSED
    assert states[1] == profiler.ProfilerState.READY
    assert states[2] == profiler.ProfilerState.RECORD
    assert states[3] == profiler.ProfilerState.RECORD_AND_RETURN


def test_launcher_cpu_sim(tmp_path):
    """2-process single-host launch (reference fake-cluster trick)."""
    import subprocess
    import sys
    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys\n"
        # single atomic write: the two ranks' stdout interleaves otherwise
        "sys.stdout.write('rank %s world %s\\n' % (\n"
        "    os.environ['PADDLE_TRAINER_ID'],\n"
        "    os.environ['PADDLE_TRAINERS_NUM']))\n")
    res = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", str(script)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": "/root/repo",
             "JAX_PLATFORMS": "cpu"})
    assert res.returncode == 0, res.stderr
    out = res.stdout
    assert "rank 0 world 2" in out and "rank 1 world 2" in out


def test_checkpoint_reshards_across_mesh_change(tmp_path):
    """Save sharded over one mesh layout, load into a DIFFERENT layout
    (the reference's changed-mesh load, semi_auto_parallel_checkpoint_*
    tests)."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import save_state_dict, load_state_dict

    mesh_a = dist.ProcessMesh(
        np.arange(8).reshape(8), dim_names=["x"])
    mesh_b = dist.ProcessMesh(
        np.arange(8).reshape(4, 2), dim_names=["x", "y"])

    w = paddle.to_tensor(
        np.arange(64, dtype=np.float32).reshape(8, 8))
    w_a = dist.shard_tensor(w, mesh_a, [dist.Shard(0)])
    sd = {"w": w_a, "step": 7}
    save_state_dict(sd, str(tmp_path / "ckpt"))

    # target: same logical tensor, sharded over BOTH axes of mesh_b
    tgt = paddle.to_tensor(np.zeros((8, 8), np.float32))
    tgt_b = dist.shard_tensor(tgt, mesh_b,
                              [dist.Shard(0), dist.Shard(1)])
    out = {"w": tgt_b, "step": 0}
    load_state_dict(out, str(tmp_path / "ckpt"))
    np.testing.assert_allclose(np.asarray(out["w"]._data),
                               np.arange(64).reshape(8, 8))
    # placement of the loaded tensor is the TARGET's (2x4 local shards
    # over the 4x2 mesh), not the saved 1-D layout (1x8 shards)
    shard_shape = out["w"]._data.addressable_shards[0].data.shape
    assert tuple(shard_shape) == (2, 4), shard_shape
    # python scalars round-trip too (step counters on resume)
    assert out["step"] == 7
