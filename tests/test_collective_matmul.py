"""Ring-overlapped collective matmuls (parallel/collective_matmul.py)
vs the unfused all_gather-then-matmul / matmul-then-reduce_scatter
references on the 8-virtual-device mesh."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from paddle_tpu.parallel.collective_matmul import (all_gather_matmul,
                                                   matmul_reduce_scatter)

N = 8
rng = np.random.RandomState(0)


def _mesh():
    return Mesh(np.asarray(jax.devices()[:N]), ("tp",))


def test_all_gather_matmul_matches_reference():
    # the Megatron column-parallel shape: x sequence-sharded, w
    # column-sharded -> per-device output is its [n*s, f/tp] slice
    s, k, f = 4, 16, 16
    x = jnp.asarray(rng.randn(N * s, k).astype(np.float32))
    w = jnp.asarray(rng.randn(k, f).astype(np.float32))
    mesh = _mesh()

    def ring(xs, ws):
        return all_gather_matmul(xs, ws, "tp")

    def plain(xs, ws):
        return lax.all_gather(xs, "tp", tiled=True) @ ws

    specs = dict(in_specs=(P("tp", None), P(None, "tp")),
                 out_specs=P(None, "tp"))
    out_ring = jax.jit(shard_map(ring, mesh=mesh, **specs))(x, w)
    out_ref = jax.jit(shard_map(plain, mesh=mesh, **specs))(x, w)
    np.testing.assert_allclose(np.asarray(out_ring),
                               np.asarray(out_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out_ring),
                               np.asarray(x @ w), rtol=1e-4,
                               atol=1e-5)


def test_matmul_reduce_scatter_matches_reference():
    m, k, f = 16, 32, 8          # k sharded over tp
    x = jnp.asarray(rng.randn(m, k).astype(np.float32))
    w = jnp.asarray(rng.randn(k, f).astype(np.float32))
    mesh = _mesh()

    def ring(xs, ws):
        return matmul_reduce_scatter(xs, ws, "tp")

    def plain(xs, ws):
        full = xs @ ws
        return lax.psum_scatter(full, "tp", scatter_dimension=0,
                                tiled=True)

    out_ring = jax.jit(shard_map(
        ring, mesh=mesh, in_specs=(P(None, "tp"), P("tp", None)),
        out_specs=P("tp", None)))(x, w)
    out_ref = jax.jit(shard_map(
        plain, mesh=mesh, in_specs=(P(None, "tp"), P("tp", None)),
        out_specs=P("tp", None)))(x, w)
    np.testing.assert_allclose(np.asarray(out_ring),
                               np.asarray(out_ref), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(out_ring),
                               np.asarray(x @ w), rtol=1e-4,
                               atol=1e-4)


def test_column_then_row_parallel_layer_pair():
    """Megatron pair: Y = gelu(all_gather(x) @ W1_col); out =
    reduce_scatter(Y @ W2_row) — the SP linear sandwich built from the
    two ring primitives end-to-end."""
    s, h, ffn = 2, 16, 32
    x = jnp.asarray(rng.randn(N * s, h).astype(np.float32))
    w1 = jnp.asarray(rng.randn(h, ffn).astype(np.float32))
    w2 = jnp.asarray(rng.randn(ffn, h).astype(np.float32))
    mesh = _mesh()

    def pair(xs, w1s, w2s):
        y = jax.nn.gelu(all_gather_matmul(xs, w1s, "tp"))
        return matmul_reduce_scatter(y, w2s, "tp")

    out = jax.jit(shard_map(
        pair, mesh=mesh,
        in_specs=(P("tp", None), P(None, "tp"), P("tp", None)),
        out_specs=P("tp", None)))(x, w1, w2)
    ref = jax.nn.gelu(x @ w1) @ w2
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_grad_flows_through_ring_matmuls():
    s, k, f = 2, 8, 16
    x = jnp.asarray(rng.randn(N * s, k).astype(np.float32))
    w = jnp.asarray(rng.randn(k, f).astype(np.float32))
    mesh = _mesh()

    def loss(x, w):
        def body(xs, ws):
            return all_gather_matmul(xs, ws, "tp")
        out = shard_map(body, mesh=mesh,
                        in_specs=(P("tp", None), P(None, "tp")),
                        out_specs=P(None, "tp"))(x, w)
        return jnp.sum(out ** 2)

    g_ring = jax.jit(jax.grad(loss, argnums=(0, 1)))(x, w)
    g_ref = jax.grad(lambda x, w: jnp.sum((x @ w) ** 2),
                     argnums=(0, 1))(x, w)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# Round 3: collective matmul WIRED into the SP linears and the hybrid
# engine (VERDICT r2 item 4) — parity with the constraint path, flag on.
# ---------------------------------------------------------------------------
def test_sp_linears_with_collective_matmul_match_constraint_path():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.distributed.fleet import (
        ColumnSequenceParallelLinear, RowSequenceParallelLinear)
    from paddle_tpu.distributed.fleet.sequence_parallel_utils import (
        all_gather, scatter)
    from paddle_tpu.distributed.mesh import ProcessMesh, set_mesh

    mesh = ProcessMesh(shape=[4], dim_names=["mp"])
    set_mesh(mesh)
    try:
        paddle.seed(11)
        col = ColumnSequenceParallelLinear(16, 32, gather_output=False)
        row = RowSequenceParallelLinear(32, 16, input_is_parallel=True)
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 8, 16).astype("float32"))
        xs = scatter(x)

        set_flags({"FLAGS_collective_matmul": False})
        y_ref = all_gather(row(col(xs))).numpy()

        set_flags({"FLAGS_collective_matmul": True})
        y_cm = all_gather(row(col(xs))).numpy()
        np.testing.assert_allclose(y_cm, y_ref, rtol=1e-4, atol=1e-5)
    finally:
        set_flags({"FLAGS_collective_matmul": False})
        set_mesh(None)


def test_sp_linears_collective_matmul_autodiff():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.distributed.fleet import ColumnSequenceParallelLinear
    from paddle_tpu.distributed.fleet.sequence_parallel_utils import \
        scatter
    from paddle_tpu.distributed.mesh import ProcessMesh, set_mesh

    mesh = ProcessMesh(shape=[4], dim_names=["mp"])
    set_mesh(mesh)
    try:
        grads = {}
        for flag in (False, True):
            set_flags({"FLAGS_collective_matmul": flag})
            paddle.seed(3)
            col = ColumnSequenceParallelLinear(16, 32,
                                               gather_output=False)
            x = paddle.to_tensor(np.random.RandomState(2).randn(
                2, 8, 16).astype("float32"))
            xs = scatter(x)
            loss = paddle.mean(col(xs) ** 2)
            loss.backward()
            grads[flag] = col.weight.grad.numpy()
        np.testing.assert_allclose(grads[True], grads[False],
                                   rtol=1e-4, atol=1e-5)
    finally:
        set_flags({"FLAGS_collective_matmul": False})
        set_mesh(None)


def test_hybrid_engine_collective_matmul_loss_parity():
    """dp1 x tp4 + sp with collective_matmul on vs off: compiled train
    step loss parity (the one-flag-flip multi-chip readiness check)."""
    import numpy as np
    import jax
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models.gpt_hybrid import ParallelConfig, setup
    cfg = GPTConfig.tiny()
    ids = np.random.default_rng(3).integers(0, 256, (4, 16))
    losses = {}
    for cm in (False, True):
        pcfg = ParallelConfig(dp=1, pp=1, tp=4, sp=True,
                              collective_matmul=cm, remat=False)
        mesh, params, opt_state, step = setup(cfg, pcfg, seed=0,
                                              devices=jax.devices()[:4])
        with mesh:
            params, opt_state, loss = step(params, opt_state, (ids, ids))
            params, opt_state, loss2 = step(params, opt_state,
                                            (ids, ids))
        losses[cm] = (float(loss), float(loss2))
    np.testing.assert_allclose(losses[True], losses[False], rtol=2e-4)


def test_cm_under_pp_upstream_wall():
    """CANARY (VERDICT r3 item 5 negative result): collective matmul
    under pp>1 via a NESTED region needs an inner tp-manual shard_map
    whose operands vary over the outer pp axis; Shardy's verifier
    rejects the combination when a remat'd ring runs under the pp
    scan's vjp ('manual axes must come before free axes' — rank-1
    operands squash vma {pp, tp} onto one dim). THIS TEST ASSERTS THE
    REJECTION STILL HAPPENS: when a jax upgrade makes it pass, flip
    gpt_hybrid._use_cm's pp==1 gate and the planner's
    collective_matmul property, and turn this into a parity test.
    Minimal structure: jax.checkpoint(stage-with-tp-ring) under scan +
    vjp inside a pp-manual region. A standalone upstreamable
    reproducer of the same wall (with the shallower failure modes
    peeled off) lives in benchmarks/probes/_cm_repro.py.

    Round-5 note: the CAPABILITY is delivered under pp>1 anyway by the
    manual-tp stage body (tp manual at the SAME level as pp, ring via
    collective_matmul.sp_*_matmul_local, no nested region —
    models/gpt_manual_tp.py); this canary tracks only the upstream
    limit of the nested formulation the GSPMD-auto engines would
    need."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax import shard_map as sm
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.parallel.collective_matmul import (sp_column_matmul,
                                                       sp_row_matmul)

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("pp", "tp"))
    B, S, H = 2, 8, 8

    def V(t):
        def one(a):
            vma = getattr(jax.typeof(a), "vma", frozenset())
            return a if "pp" in vma else lax.pcast(a, ("pp",),
                                                   to="varying")
        return jax.tree_util.tree_map(one, t)

    @jax.checkpoint
    def stage(w, x):
        h = sp_column_matmul(x, w, mesh, "tp")
        return sp_row_matmul(jax.nn.gelu(h), w, mesh, "tp")

    def outer(blocks, x):
        w = blocks[0]

        def tick(carry, t):
            _, vjpfn = jax.vjp(lambda xx: stage(w, xx), carry)
            (dx,) = vjpfn(V(jnp.ones_like(carry)))
            return V(dx), None

        out, _ = lax.scan(tick, V(x), jnp.arange(3))
        return out[None]

    blocks = jnp.ones((2, H, H))
    x = jnp.ones((B, S, H))
    # match ANY exception: jax upgrades may shift between the three
    # documented failure modes — the canary must only signal on genuine
    # compilation success, not on a reworded rejection
    with pytest.raises(Exception):
        jax.jit(sm(outer, mesh=mesh, axis_names={"pp"},
                   in_specs=(P("pp"), P(None)),
                   out_specs=P("pp", None, None, None)))(blocks, x)
