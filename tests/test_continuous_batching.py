"""Continuous batching over the dense fixed-capacity cache (round 5).

Reference capability being matched: block_multihead_attention's paged
KV serving — variable-length multi-request batches with mid-flight
admission/retirement (/root/reference/python/paddle/incubate/nn/
functional/block_multihead_attention.py). The TPU design keeps a
static [slots, capacity] cache; the dynamism is host-side slot
management over two fixed executables (admit-per-bucket + one decode).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.decode import (ContinuousBatchingSession,
                                         DecodeSession)
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(7)
    return LlamaForCausalLM(LlamaConfig.tiny())


def _isolated(model, ids, n):
    return DecodeSession(model, 64).generate(
        paddle.to_tensor(np.asarray(ids)[None]),
        max_new_tokens=n).numpy()[0]


def test_overlapping_lifetimes_match_isolated_decodes(tiny_model):
    """Three requests with different prompts/budgets through TWO slots:
    r2 is admitted mid-flight into the slot r1 frees, while r0 keeps
    decoding — every per-request output must equal the isolated
    single-request greedy decode, and the executable count stays at
    (buckets used, 1)."""
    m = tiny_model
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, 256, (n,)).astype(np.int32)
               for n in (5, 3, 9)]
    budgets = [12, 4, 6]

    sess = ContinuousBatchingSession(m, max_slots=2, max_length=64)
    rids = [sess.submit(p, b) for p, b in zip(prompts, budgets)]

    # with 2 slots, r2 waits in the queue; r1 (budget 4) retires first
    # and frees its slot while r0 (budget 12) is still decoding
    completed = []
    steps = 0
    while (sess._queue or sess._running) and steps < 64:
        done = sess.step()
        completed.extend(done)
        steps += 1
        if steps == 1:
            # after the first step both slots are occupied, r2 queued
            assert len(sess._running) == 2 and len(sess._queue) == 1
    out = sess.run()

    # r1 finished before r0 (overlapping lifetimes, not FIFO completion)
    assert completed.index(rids[1]) < completed.index(rids[0])

    for rid, prompt, budget in zip(rids, prompts, budgets):
        ref = _isolated(m, prompt, budget)
        np.testing.assert_array_equal(out[rid], ref,
                                      err_msg=f"request {rid}")

    n_admit, n_decode = sess.executable_counts()
    assert n_decode == 1, "decode must stay one executable"
    assert n_admit <= 3, "admit is bounded by the bucket count"


def test_slot_reuse_many_requests_bounded_executables(tiny_model):
    """Eight short requests through two slots: every slot is reused
    several times; outputs still match isolated decodes and the
    executable pool does not grow with request count."""
    m = tiny_model
    rng = np.random.RandomState(11)
    sess = ContinuousBatchingSession(m, max_slots=2, max_length=64)
    reqs = []
    for i in range(8):
        p = rng.randint(0, 256, (rng.randint(2, 12),)).astype(np.int32)
        b = int(rng.randint(2, 6))
        reqs.append((sess.submit(p, b), p, b))
    out = sess.run()
    for rid, p, b in reqs:
        np.testing.assert_array_equal(out[rid], _isolated(m, p, b),
                                      err_msg=f"request {rid}")
    n_admit, n_decode = sess.executable_counts()
    assert n_decode == 1 and n_admit <= 4


def test_eos_retires_slot_early(tiny_model):
    """A request whose sampled token hits eos retires before its budget
    and frees the slot for the queue."""
    m = tiny_model
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, 256, (4,)).astype(np.int32)
    # find what greedy emits first so we can use it as "eos"
    first = int(_isolated(m, prompt, 2)[len(prompt)])
    sess = ContinuousBatchingSession(m, max_slots=1, max_length=64,
                                     eos_token_id=first)
    rid = sess.submit(prompt, 10)
    rid2 = sess.submit(rng.randint(0, 256, (3,)).astype(np.int32), 2)
    out = sess.run()
    # retired at the eos token, well under budget
    assert len(out[rid]) == len(prompt) + 1
    assert out[rid][-1] == first
    assert len(out[rid2]) == 3 + 2


def test_capacity_guard(tiny_model):
    sess = ContinuousBatchingSession(tiny_model, max_slots=1,
                                     max_length=16)
    with pytest.raises(ValueError, match="capacity"):
        sess.submit(np.zeros(10, np.int32), 8)


def test_sync_every_batched_retirement_same_outputs(tiny_model):
    """sync_every>1 fetches token blocks instead of per-step tokens;
    outputs are unchanged (retirement lags, wasted decodes discarded,
    slot caches reset on admission)."""
    m = tiny_model
    rng = np.random.RandomState(21)
    reqs = [(rng.randint(0, 256, (rng.randint(2, 10),))
             .astype(np.int32), int(rng.randint(2, 7)))
            for _ in range(5)]
    sess = ContinuousBatchingSession(m, max_slots=2, max_length=64,
                                    sync_every=4)
    rids = [sess.submit(p, b) for p, b in reqs]
    out = sess.run()
    for rid, (p, b) in zip(rids, reqs):
        np.testing.assert_array_equal(out[rid], _isolated(m, p, b),
                                      err_msg=f"request {rid}")
    assert sess.executable_counts()[1] == 1


def test_run_delivers_each_request_once(tiny_model):
    """run() returns only undelivered completions and releases them —
    a second drain never re-delivers (review finding); request_id
    collisions with IN-FLIGHT requests are refused, while delivered
    ids become reusable (so a long-lived serving session's id set does
    not grow forever)."""
    m = tiny_model
    rng = np.random.RandomState(31)
    sess = ContinuousBatchingSession(m, max_slots=1, max_length=64)
    p1 = rng.randint(0, 256, (4,)).astype(np.int32)
    rid1 = sess.submit(p1, 3, request_id=5)
    out1 = sess.run()
    assert set(out1) == {5}
    p2 = rng.randint(0, 256, (6,)).astype(np.int32)
    rid2 = sess.submit(p2, 2)
    assert rid2 != 5
    # rid2 is in flight: a colliding explicit id is refused
    with pytest.raises(ValueError, match="already in use"):
        sess.submit(p1, 2, request_id=rid2)
    out2 = sess.run()
    assert set(out2) == {rid2}, "earlier results must not re-deliver"
    # delivered ids are released — reuse is allowed and tracked afresh
    assert sess._used_rids == set()
    rid3 = sess.submit(p1, 2, request_id=rid2)
    assert rid3 == rid2
    out3 = sess.run()
    assert set(out3) == {rid3}


def test_decode_block_mode_same_outputs(tiny_model):
    """decode_block=k emits [slots, k] token blocks per dispatch (one
    while_loop program — the DecodeSession block decoder over the slot
    batch); outputs are unchanged and the executable count stays 1."""
    m = tiny_model
    rng = np.random.RandomState(41)
    reqs = [(rng.randint(0, 256, (rng.randint(2, 10),))
             .astype(np.int32), int(rng.randint(2, 9)))
            for _ in range(5)]
    sess = ContinuousBatchingSession(m, max_slots=2, max_length=64,
                                     decode_block=4)
    rids = [sess.submit(p, b) for p, b in reqs]
    out = sess.run()
    for rid, (p, b) in zip(rids, reqs):
        np.testing.assert_array_equal(out[rid], _isolated(m, p, b),
                                      err_msg=f"request {rid}")
    assert sess.executable_counts()[1] == 1
