"""Multi-process DataLoader tests (reference:
test/legacy_test/test_dataloader_* — worker processes, ordering,
worker_init_fn, error propagation)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, Dataset, get_worker_info


class Items(Dataset):
    def __init__(self, n=32):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.full((4,), i, np.float32), np.int64(i % 3)


class Failing(Items):
    def __getitem__(self, i):
        raise ValueError("bad item")


def test_mp_loader_matches_single_process_order():
    mp = [b[0].numpy() for b in DataLoader(Items(), batch_size=8,
                                           num_workers=3)]
    sp = [b[0].numpy() for b in DataLoader(Items(), batch_size=8,
                                           num_workers=0)]
    assert len(mp) == len(sp) == 4
    for a, b in zip(mp, sp):
        np.testing.assert_allclose(a, b)


def test_mp_loader_dict_and_labels():
    class DictDs(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return {"x": np.ones(2, np.float32) * i, "y": np.int64(i)}

    out = list(DataLoader(DictDs(), batch_size=4, num_workers=2))
    assert set(out[0]) == {"x", "y"}
    np.testing.assert_allclose(out[0]["y"].numpy(), [0, 1, 2, 3])


def test_worker_init_fn_and_info():
    def init_fn(wid):
        info = get_worker_info()
        assert info is not None and info.id == wid
        assert info.num_workers == 2

    out = list(DataLoader(Items(16), batch_size=4, num_workers=2,
                          worker_init_fn=init_fn))
    assert len(out) == 4


def test_worker_error_propagates():
    with pytest.raises(RuntimeError, match="worker"):
        list(DataLoader(Failing(8), batch_size=4, num_workers=2))


def test_main_process_worker_info_is_none():
    assert get_worker_info() is None
