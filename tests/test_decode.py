"""TPU-native decode/serving path tests (VERDICT r2 item 1).

Covers: static-cache generation numerics vs the legacy concat path,
zero-recompile guarantees (executable-cache stability), the
MultiHeadAttention fixed cache, the real masked_multihead_attention, and
int8-native serving export/load.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def _legacy_greedy(m, ids, n):
    """Round-2 concat-cache greedy loop (the numerics oracle)."""
    with paddle.no_grad():
        caches = m.llama.init_cache(ids.shape[0])
        logits, caches = m.llama(ids, 0, caches)
        out = [ids]
        pos = ids.shape[1]
        for _ in range(n):
            nxt = paddle.argmax(logits[:, -1], axis=-1, keepdim=True)
            out.append(nxt)
            logits, caches = m.llama(nxt, pos, caches)
            pos += 1
        return paddle.concat(out, axis=1).numpy()


@pytest.fixture(scope="module")
def tiny_llama():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    return m


def test_static_cache_generation_matches_concat_path(tiny_llama):
    m = tiny_llama
    paddle.seed(1)
    ids = paddle.randint(0, 256, [2, 8])
    ref = _legacy_greedy(m, ids, 6)
    new = m.generate(ids, max_new_tokens=6, temperature=0.0).numpy()
    np.testing.assert_array_equal(ref, new)


def test_decode_zero_recompiles_after_warmup(tiny_llama):
    m = tiny_llama
    paddle.seed(2)
    ids = paddle.randint(0, 256, [2, 8])
    m.generate(ids, max_new_tokens=4, temperature=0.0)
    sess = next(iter(m._decode_sessions.values()))
    pre0, dec0 = sess.executable_counts()
    assert dec0 == 1
    # more tokens, different prompt content, repeated calls: the decode
    # executable count must not move
    m.generate(ids, max_new_tokens=12, temperature=0.0)
    paddle.seed(3)
    ids2 = paddle.randint(0, 256, [2, 8])
    m.generate(ids2, max_new_tokens=9, temperature=0.0)
    pre1, dec1 = sess.executable_counts()
    assert dec1 == 1
    assert pre1 == pre0 == 1


def test_prefill_bucketing_bounds_executables(tiny_llama):
    m = tiny_llama
    paddle.seed(4)
    # prompt lengths 5 and 7 share the 16-bucket -> one prefill program
    ids5 = paddle.randint(0, 256, [1, 5])
    ids7 = paddle.randint(0, 256, [1, 7])
    m.generate(ids5, max_new_tokens=3, temperature=0.0)
    sess = next(iter(m._decode_sessions.values()))
    n0 = sess.executable_counts()[0]
    m.generate(ids7, max_new_tokens=3, temperature=0.0)
    assert sess.executable_counts()[0] == n0


def test_generate_sampling_temperature_runs(tiny_llama):
    m = tiny_llama
    paddle.seed(5)
    ids = paddle.randint(0, 256, [2, 6])
    out = m.generate(ids, max_new_tokens=5, temperature=0.8, top_p=0.9,
                     seed=7)
    assert out.shape == [2, 11]
    # same seed reproduces; different seed (usually) differs
    out2 = m.generate(ids, max_new_tokens=5, temperature=0.8, top_p=0.9,
                      seed=7)
    np.testing.assert_array_equal(out.numpy(), out2.numpy())


def test_gpt_static_cache_matches_full_forward():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    m = GPTForCausalLM(GPTConfig.tiny())
    m.eval()
    ids = paddle.randint(0, 256, [2, 12])
    with paddle.no_grad():
        full = m(ids)
        caches = m.init_cache(2, max_length=32)
        logits, caches = m.forward_with_cache(ids, caches)
    np.testing.assert_allclose(full.numpy(), logits.numpy(),
                               rtol=2e-4, atol=2e-4)
    # incremental: feed one more token, compare against full forward
    paddle.seed(1)
    nxt = paddle.randint(0, 256, [2, 1])
    with paddle.no_grad():
        step, caches = m.forward_with_cache(nxt, caches)
        full2 = m(paddle.concat([ids, nxt], axis=1))
    np.testing.assert_allclose(full2[:, -1:].numpy(), step.numpy(),
                               rtol=2e-4, atol=2e-4)


def test_gpt_generate_runs():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    m = GPTForCausalLM(GPTConfig.tiny())
    ids = paddle.randint(0, 256, [1, 8])
    out = m.generate(ids, max_new_tokens=4)
    assert out.shape == [1, 12]


def test_llama_static_cache_incremental_matches_full(tiny_llama):
    m = tiny_llama
    paddle.seed(6)
    ids = paddle.randint(0, 256, [2, 12])
    with paddle.no_grad():
        full = m.llama(ids)
        caches = m.init_cache(2, max_length=32)
        logits, caches = m.forward_with_cache(ids, caches)
        np.testing.assert_allclose(full.numpy(), logits.numpy(),
                                   rtol=2e-4, atol=2e-4)
        # per-layer cache lengths advanced to 12
        assert int(caches[0].length.numpy()[0]) == 12


def test_multihead_attention_decode_cache_matches_concat():
    paddle.seed(0)
    mha = nn.MultiHeadAttention(32, 4)
    mha.eval()
    x = paddle.randn([2, 6, 32])
    with paddle.no_grad():
        # concat-cache path (reference semantics)
        ccache = mha.gen_cache(x)
        outs_concat = []
        for i in range(6):
            o, ccache = mha(x[:, i:i + 1], x[:, i:i + 1], x[:, i:i + 1],
                            None, ccache)
            outs_concat.append(o.numpy())
        # fixed-capacity decode path
        dcache = mha.gen_cache(x, max_length=16)
        outs_static = []
        for i in range(6):
            o, dcache = mha(x[:, i:i + 1], x[:, i:i + 1], x[:, i:i + 1],
                            None, dcache)
            outs_static.append(o.numpy())
    for a, b in zip(outs_concat, outs_static):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)
    assert int(dcache.length.numpy()[0]) == 6
    assert dcache.k.shape[1] == 16     # capacity never grew


def test_masked_multihead_attention_real():
    """The incubate decode kernel against a numpy oracle."""
    from paddle_tpu.incubate.nn import functional as IF
    rng = np.random.default_rng(0)
    B, H, C, D = 2, 4, 16, 8
    lens = np.array([5, 9], np.int32)
    cache = rng.standard_normal((2, B, H, C, D)).astype(np.float32)
    x = rng.standard_normal((B, 3 * H * D)).astype(np.float32)
    out, new_cache = IF.masked_multihead_attention(
        paddle.to_tensor(x), cache_kv=paddle.to_tensor(cache),
        sequence_lengths=paddle.to_tensor(lens))
    qkv = x.reshape(B, 3, H, D)
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
    ref = np.empty((B, H, D), np.float32)
    nc = cache.copy()
    for b in range(B):
        L = lens[b]
        nc[0, b, :, L] = k[b]
        nc[1, b, :, L] = v[b]
        for h in range(H):
            ks = nc[0, b, h, :L + 1]                    # [L+1, D]
            vs = nc[1, b, h, :L + 1]
            logits = ks @ q[b, h] / np.sqrt(D)
            p = np.exp(logits - logits.max())
            p /= p.sum()
            ref[b, h] = p @ vs
    np.testing.assert_allclose(out.numpy().reshape(B, H, D), ref,
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(new_cache.numpy(), nc, rtol=1e-5,
                               atol=1e-5)


def test_int8_native_serving_export_roundtrip(tmp_path):
    """PTQ -> export int8 payload -> load into fresh model: weights live
    as int8 in memory, logits match the QDQ-emulated predictor."""
    from paddle_tpu import inference
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    import jax.numpy as jnp
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    cfg = inference.Config()
    cfg.set_layer(m)
    cfg.enable_int8_weight_only()
    pred = inference.create_predictor(cfg)
    ids = paddle.randint(0, 256, [2, 8])
    with paddle.no_grad():
        qdq_logits = m(ids).numpy()     # QDQ-emulated numerics

    path = str(tmp_path / "llama_int8.npz")
    inference.save_int8_model(pred, path)

    paddle.seed(0)
    fresh = LlamaForCausalLM(LlamaConfig.tiny())
    fresh.eval()
    n = inference.load_int8_model(fresh, path)
    swapped = [s for _, s in n.named_sublayers()
               if isinstance(s, inference.Int8Linear)]
    assert len(swapped) > 0
    # int8 actually lives in memory (the HBM payoff)
    assert swapped[0].weight_q._data.dtype == jnp.int8
    with paddle.no_grad():
        int8_logits = fresh(ids).numpy()
    np.testing.assert_allclose(qdq_logits, int8_logits, rtol=2e-4,
                               atol=2e-4)


def test_cache_overflow_raises_eagerly():
    from paddle_tpu.inference.decode import (init_static_cache,
                                             cache_attention)
    import jax.numpy as jnp
    cache = init_static_cache(1, 4, 2, 8)
    cache = cache._replace(length=paddle.to_tensor(
        np.array([4], np.int32)))
    q = paddle.randn([1, 1, 2, 8])
    with pytest.raises(ValueError, match="overflow"):
        cache_attention(q, q, q, cache)


def test_eos_pins_finished_sequences():
    from paddle_tpu.inference.decode import DecodeSession
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    ids = paddle.randint(0, 256, [2, 6])
    # discover what greedy decoding emits at step 2 for sequence 0, then
    # declare that token the eos: everything after must be pinned to it
    probe = DecodeSession(m, 32).generate(ids, max_new_tokens=6).numpy()
    eos = int(probe[0, 7])
    sess = DecodeSession(m, 32, eos_token_id=eos)
    out = sess.generate(ids, max_new_tokens=6).numpy()
    gen0 = out[0, 6:]
    hit = np.argmax(gen0 == eos)
    assert gen0[hit] == eos
    assert (gen0[hit:] == eos).all(), gen0


def test_predictor_generate_serving(tiny_llama):
    from paddle_tpu import inference
    cfg = inference.Config()
    cfg.set_layer(tiny_llama)
    cfg.enable_decode(max_length=32)
    pred = inference.create_predictor(cfg)
    paddle.seed(8)
    ids = paddle.randint(0, 256, [2, 8])
    out = pred.generate(ids, max_new_tokens=5)
    assert out.shape == [2, 13]
    ref = _legacy_greedy(tiny_llama, ids, 5)
    np.testing.assert_array_equal(ref, out.numpy())
    assert pred.stats["runs"] == 1


def test_block_decode_exact_parity_with_per_step(tiny_llama):
    """The single-program lax.while_loop block decoder must emit exactly
    the tokens of the per-step path (greedy), with ONE decode
    executable however many blocks run (short final block included —
    the step count is a traced operand, not a shape)."""
    m = tiny_llama
    paddle.seed(11)
    ids = paddle.randint(0, 256, [2, 8])
    ref = m.generate(ids, max_new_tokens=13, temperature=0.0).numpy()
    out = m.generate(ids, max_new_tokens=13, temperature=0.0,
                     decode_block=4).numpy()
    np.testing.assert_array_equal(ref, out)
    sess = next(s for k, s in m._decode_sessions.items() if k[3] == 4)
    pre, dec = sess.executable_counts()
    assert dec == 1
    # different lengths / prompts reuse the same block executable
    out2 = m.generate(ids, max_new_tokens=6, temperature=0.0,
                      decode_block=4).numpy()
    ref2 = m.generate(ids, max_new_tokens=6, temperature=0.0).numpy()
    np.testing.assert_array_equal(ref2, out2)
    assert sess.executable_counts()[1] == 1


def test_block_decode_eos_early_exit(tiny_llama):
    """All-finished batches stop dispatching blocks and back-fill eos —
    token-for-token identical to the per-step path's pinning."""
    from paddle_tpu.inference.decode import DecodeSession
    m = tiny_llama
    paddle.seed(12)
    ids = paddle.randint(0, 256, [2, 6])
    probe = DecodeSession(m, 64).generate(ids, max_new_tokens=6).numpy()
    eos = int(probe[0, 7])
    ref = DecodeSession(m, 64, eos_token_id=eos).generate(
        ids, max_new_tokens=20).numpy()
    sess = DecodeSession(m, 64, eos_token_id=eos, decode_block=4)
    out = sess.generate(ids, max_new_tokens=20).numpy()
    gen = out[0, 6:]
    hit = np.argmax(gen == eos)
    assert (gen[hit:] == eos).all(), gen
    np.testing.assert_array_equal(ref[1], out[1])
    assert sess.executable_counts()[1] == 1


def test_decode_session_top_k_restricts_support(tiny_llama):
    """top_k sampling: every sampled token lies in the top-k of the
    step's logits (checked via a k=1 session equaling greedy)."""
    from paddle_tpu.inference.decode import DecodeSession
    m = tiny_llama
    paddle.seed(3)
    ids = paddle.randint(0, 256, [2, 6])
    greedy = DecodeSession(m, 32).generate(
        ids, max_new_tokens=5).numpy()
    # temperature>0 but k=1 collapses the support to the argmax
    k1 = DecodeSession(m, 32, temperature=1.0, top_k=1).generate(
        ids, max_new_tokens=5, seed=11).numpy()
    np.testing.assert_array_equal(greedy, k1)
    # k=5 with a seed reproduces itself
    s = DecodeSession(m, 32, temperature=0.9, top_k=5)
    a = s.generate(ids, max_new_tokens=5, seed=7).numpy()
    b = s.generate(ids, max_new_tokens=5, seed=7).numpy()
    np.testing.assert_array_equal(a, b)
    # top_k larger than the vocab is clamped (no shape error deep in
    # the compiled step) and degrades to unrestricted sampling
    big = DecodeSession(m, 32, temperature=0.9, top_k=10**6)
    out = big.generate(ids, max_new_tokens=3, seed=7).numpy()
    assert out.shape == (2, 9)
