"""Distributed semantics on the 8-device virtual CPU mesh (the reference's
CPU fake-cluster trick, SURVEY §4.2): shard_tensor/reshard placements,
DP loss parity vs single-device, TP layer sharding + math parity."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn


def test_mesh_and_shard_tensor():
    mesh = dist.ProcessMesh(shape=[2, 4], dim_names=["dp", "mp"])
    x = paddle.randn([8, 16])
    d = dist.shard_tensor(x, mesh, [dist.Shard(0), dist.Shard(1)])
    np.testing.assert_allclose(d.numpy(), x.numpy())
    pls = d.placements
    assert pls[0] == dist.Shard(0)
    assert pls[1] == dist.Shard(1)
    assert d.process_mesh.shape == [2, 4]


def test_reshard():
    mesh = dist.ProcessMesh(shape=[8], dim_names=["x"])
    x = paddle.arange(64, dtype="float32").reshape([8, 8])
    d = dist.shard_tensor(x, mesh, [dist.Shard(0)])
    r = dist.reshard(d, mesh, [dist.Replicate()])
    assert r.placements[0] == dist.Replicate()
    np.testing.assert_allclose(r.numpy(), x.numpy())
    s1 = dist.reshard(r, mesh, [dist.Shard(1)])
    assert s1.placements[0] == dist.Shard(1)
    np.testing.assert_allclose(s1.numpy(), x.numpy())


def test_sharded_math_matches_dense():
    mesh = dist.ProcessMesh(shape=[8], dim_names=["mp"])
    rng = np.random.RandomState(0)
    a = rng.randn(16, 32).astype(np.float32)
    b = rng.randn(32, 8).astype(np.float32)
    xa = dist.shard_tensor(paddle.to_tensor(a), mesh, [dist.Shard(1)])
    xb = dist.shard_tensor(paddle.to_tensor(b), mesh, [dist.Shard(0)])
    out = paddle.matmul(xa, xb)  # contraction over sharded dim -> psum
    np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-4, atol=1e-5)


def test_data_parallel_loss_parity():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = paddle.randn([16, 4])
    y = paddle.randint(0, 2, [16])
    loss_fn = nn.CrossEntropyLoss()
    ref_loss = loss_fn(model(x), y)
    ref_loss.backward()
    ref_grads = {n: p.grad.numpy().copy()
                 for n, p in model.named_parameters()}
    model.clear_gradients()

    dp = dist.DataParallel(model)
    loss = loss_fn(dp(x), y)
    loss.backward()
    np.testing.assert_allclose(loss.numpy(), ref_loss.numpy(), rtol=1e-5)
    for n, p in model.named_parameters():
        np.testing.assert_allclose(p.grad.numpy(), ref_grads[n],
                                   rtol=1e-4, atol=1e-5)


def test_fleet_tp_layers_parity():
    import paddle_tpu.distributed.fleet as fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4,
                               "pp_degree": 1, "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_model_parallel_world_size() == 4
    assert hcg.get_data_parallel_world_size() == 2

    paddle.seed(1)
    col = fleet.ColumnParallelLinear(16, 32, gather_output=False,
                                     has_bias=True)
    row = fleet.RowParallelLinear(32, 16, input_is_parallel=True,
                                  has_bias=True)
    # dense reference with identical weights
    ref1 = nn.Linear(16, 32)
    ref2 = nn.Linear(32, 16)
    ref1.weight.set_value(col.weight)
    ref1.bias.set_value(col.bias)
    ref2.weight.set_value(row.weight)
    ref2.bias.set_value(row.bias)

    x = paddle.randn([8, 16])
    out = row(col(x))
    ref = ref2(ref1(x))
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-5)
    # weights actually sharded over mp
    assert not col.weight._data.sharding.is_fully_replicated


def test_vocab_parallel_embedding():
    import paddle_tpu.distributed.fleet as fleet
    if fleet.get_hybrid_communicate_group() is None:
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4,
                                   "pp_degree": 1, "sharding_degree": 1}
        fleet.init(is_collective=True, strategy=strategy)
    emb = fleet.VocabParallelEmbedding(64, 16)
    ids = paddle.randint(0, 64, [4, 8])
    out = emb(ids)
    assert out.shape == [4, 8, 16]
    ref = emb.weight.numpy()[ids.numpy()]
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)


def test_recompute_grad_parity():
    paddle.seed(3)
    layer = nn.Sequential(nn.Linear(8, 8), nn.Tanh(), nn.Linear(8, 8))
    x = paddle.randn([4, 8])
    x.stop_gradient = False
    out = dist.recompute(layer, x)
    out.sum().backward()
    g_re = {n: p.grad.numpy().copy() for n, p in layer.named_parameters()}
    gx_re = x.grad.numpy().copy()
    layer.clear_gradients()
    x2 = paddle.to_tensor(x.numpy())
    x2.stop_gradient = False
    layer(x2).sum().backward()
    for n, p in layer.named_parameters():
        np.testing.assert_allclose(g_re[n], p.grad.numpy(), rtol=1e-4,
                                   atol=1e-6)
    np.testing.assert_allclose(gx_re, x2.grad.numpy(), rtol=1e-4, atol=1e-6)


def test_collective_api_smoke():
    dist.init_parallel_env()
    assert dist.get_world_size() >= 1
    t = paddle.ones([4])
    task = dist.all_reduce(t)
    task.wait()
    outs = []
    dist.all_gather(outs, t)
    assert len(outs) == dist.get_world_size()
