"""Extended distribution families + transform library tests.

Methodology mirrors the reference's distribution suite
(test/distribution/): log_prob checked against scipy.stats ground truth,
sampling checked by moment-matching, transforms checked by round-trip +
log-det-jacobian vs autodiff.
"""
import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as paddle
from paddle_tpu import distribution as D


def _lp(dist, v):
    return np.asarray(dist.log_prob(paddle.to_tensor(v)).numpy())


# ---------------------------------------------------------------------
# log_prob vs scipy
# ---------------------------------------------------------------------
def test_poisson_log_prob_and_moments():
    d = D.Poisson(paddle.to_tensor([2.0, 5.0]))
    v = np.array([1.0, 4.0])
    np.testing.assert_allclose(
        _lp(d, v), st.poisson.logpmf(v, [2.0, 5.0]), rtol=1e-5)
    s = d.sample((4000,)).numpy()
    np.testing.assert_allclose(s.mean(0), [2.0, 5.0], rtol=0.1)


def test_binomial_log_prob():
    d = D.Binomial(paddle.to_tensor(10.0), paddle.to_tensor(0.3))
    v = np.array([3.0])
    np.testing.assert_allclose(
        _lp(d, v), st.binom.logpmf(3, 10, 0.3), rtol=1e-5)
    s = d.sample((4000,)).numpy()
    np.testing.assert_allclose(s.mean(), 3.0, rtol=0.1)


def test_cauchy_log_prob_entropy_kl():
    d = D.Cauchy(1.0, 2.0)
    v = np.array([0.5])
    np.testing.assert_allclose(
        _lp(d, v), st.cauchy.logpdf(0.5, 1.0, 2.0), rtol=1e-5)
    np.testing.assert_allclose(float(d.entropy()),
                               st.cauchy.entropy(1.0, 2.0), rtol=1e-5)
    q = D.Cauchy(1.0, 2.0)
    np.testing.assert_allclose(float(D.kl_divergence(d, q)), 0.0,
                               atol=1e-6)


def test_chi2_log_prob():
    d = D.Chi2(paddle.to_tensor(3.0))
    v = np.array([2.5])
    np.testing.assert_allclose(_lp(d, v), st.chi2.logpdf(2.5, 3),
                               rtol=1e-5)


def test_student_t_log_prob():
    d = D.StudentT(4.0, 1.0, 2.0)
    v = np.array([0.0])
    np.testing.assert_allclose(
        _lp(d, v), st.t.logpdf(0.0, 4, loc=1.0, scale=2.0), rtol=1e-5)
    s = d.rsample((8000,)).numpy()
    np.testing.assert_allclose(np.median(s), 1.0, atol=0.15)


def test_mvn_log_prob_entropy_kl():
    cov = np.array([[2.0, 0.5], [0.5, 1.0]])
    loc = np.array([1.0, -1.0])
    d = D.MultivariateNormal(paddle.to_tensor(loc.astype("float32")),
                             covariance_matrix=paddle.to_tensor(
                                 cov.astype("float32")))
    ref = st.multivariate_normal(loc, cov)
    v = np.array([0.3, 0.7], "float32")
    np.testing.assert_allclose(_lp(d, v), ref.logpdf(v), rtol=1e-4)
    np.testing.assert_allclose(float(d.entropy()), ref.entropy(),
                               rtol=1e-5)
    s = d.rsample((6000,)).numpy()
    np.testing.assert_allclose(s.mean(0), loc, atol=0.15)
    np.testing.assert_allclose(np.cov(s.T), cov, atol=0.25)
    q = D.MultivariateNormal(
        paddle.to_tensor(loc.astype("float32")),
        covariance_matrix=paddle.to_tensor(cov.astype("float32")))
    np.testing.assert_allclose(float(D.kl_divergence(d, q)), 0.0,
                               atol=1e-5)


def test_continuous_bernoulli_log_prob_integrates_to_one():
    d = D.ContinuousBernoulli(paddle.to_tensor(0.3))
    xs = np.linspace(1e-4, 1 - 1e-4, 2001).astype("float32")
    p = np.exp(_lp(d, xs))
    np.testing.assert_allclose(np.trapezoid(p, xs), 1.0, rtol=1e-3)
    s = d.rsample((4000,)).numpy()
    np.testing.assert_allclose(s.mean(), float(d.mean), atol=0.03)


def test_poisson_entropy_series():
    d = D.Poisson(paddle.to_tensor(3.0))
    ks = np.arange(0, 60).astype("float32")
    logp = _lp(d, ks).astype("float64")
    pmf = np.exp(logp)
    direct = -np.sum(np.where(pmf > 1e-30, pmf * logp, 0.0))
    np.testing.assert_allclose(float(d.entropy()), direct, rtol=1e-3)


def test_exponential_family_entropy_bregman():
    # Bregman identity on a zero-carrier family: Exponential(rate) as an
    # ExponentialFamily subclass; closed-form entropy = 1 - log(rate)
    import jax.numpy as jnp

    class _Exp(D.ExponentialFamily):
        def __init__(self, rate):
            self.rate = rate
            super().__init__(tuple(rate.shape))

        @property
        def _natural_parameters(self):
            return [-self.rate]

        def _log_normalizer(self, eta):
            return -jnp.log(-eta)

    rate = paddle.to_tensor([0.5, 2.0])
    got = _Exp(rate).entropy().numpy()
    np.testing.assert_allclose(got, 1.0 - np.log([0.5, 2.0]), rtol=1e-5)


def test_independent_sums_event_dims():
    base = D.Normal(paddle.to_tensor(np.zeros((3, 4), "float32")),
                    paddle.to_tensor(np.ones((3, 4), "float32")))
    d = D.Independent(base, 1)
    assert d.batch_shape == (3,) and d.event_shape == (4,)
    v = np.random.RandomState(0).randn(3, 4).astype("float32")
    got = _lp(d, v)
    np.testing.assert_allclose(got, _lp(base, v).sum(-1), rtol=1e-5)


def test_lkj_cholesky_samples_valid():
    d = D.LKJCholesky(3, 1.5)
    L = d.sample((64,)).numpy()
    assert L.shape == (64, 3, 3)
    # rows are unit-norm (correlation cholesky), lower-triangular
    np.testing.assert_allclose((L ** 2).sum(-1), 1.0, atol=1e-5)
    assert np.allclose(np.triu(L, 1), 0.0)
    lp = _lp(d, L[0])
    assert np.isfinite(lp).all()


# ---------------------------------------------------------------------
# transforms
# ---------------------------------------------------------------------
@pytest.mark.parametrize("tr,x", [
    (lambda: D.AffineTransform(paddle.to_tensor(1.0),
                               paddle.to_tensor(2.0)), 0.7),
    (lambda: D.ExpTransform(), 0.7),
    (lambda: D.PowerTransform(paddle.to_tensor(3.0)), 0.7),
    (lambda: D.SigmoidTransform(), 0.7),
    (lambda: D.TanhTransform(), 0.7),
])
def test_transform_roundtrip_and_ldj(tr, x):
    import jax
    t = tr()
    xv = paddle.to_tensor(np.array([x], "float32"))
    y = t.forward(xv)
    back = t.inverse(y)
    np.testing.assert_allclose(back.numpy(), xv.numpy(), rtol=1e-4)
    # log|dy/dx| vs autodiff
    import jax.numpy as jnp
    fwd = {D.AffineTransform: lambda z: 1.0 + 2.0 * z,
           D.ExpTransform: lambda z: jnp.exp(z),
           D.PowerTransform: lambda z: z ** 3.0,
           D.SigmoidTransform: lambda z: 1 / (1 + jnp.exp(-z)),
           D.TanhTransform: lambda z: jnp.tanh(z)}[type(t)]
    g = jax.grad(lambda z: fwd(z))(float(x))
    np.testing.assert_allclose(float(t.forward_log_det_jacobian(xv)),
                               np.log(abs(g)), rtol=1e-4)


def test_stick_breaking_transform():
    t = D.StickBreakingTransform()
    x = paddle.to_tensor(np.array([0.3, -0.2, 0.5], "float32"))
    y = t.forward(x)
    assert y.shape[-1] == 4
    np.testing.assert_allclose(y.numpy().sum(), 1.0, rtol=1e-5)
    back = t.inverse(y)
    np.testing.assert_allclose(back.numpy(), x.numpy(), rtol=1e-4,
                               atol=1e-5)
    assert np.isfinite(float(t.forward_log_det_jacobian(x).numpy()))


def test_chain_and_reshape_transforms():
    chain = D.ChainTransform([D.AffineTransform(paddle.to_tensor(0.0),
                                                paddle.to_tensor(2.0)),
                              D.ExpTransform()])
    x = paddle.to_tensor(np.array([0.5], "float32"))
    y = chain.forward(x)
    np.testing.assert_allclose(y.numpy(), np.exp(2 * 0.5), rtol=1e-5)
    np.testing.assert_allclose(chain.inverse(y).numpy(), 0.5, rtol=1e-5)
    r = D.ReshapeTransform((2, 3), (6,))
    xr = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3))
    assert tuple(r.forward(xr).shape) == (6,)
    np.testing.assert_allclose(r.inverse(r.forward(xr)).numpy(),
                               xr.numpy())


def test_transformed_distribution_log_normal():
    base = D.Normal(paddle.to_tensor(0.5), paddle.to_tensor(0.8))
    d = D.TransformedDistribution(base, [D.ExpTransform()])
    v = np.array([1.7], "float32")
    np.testing.assert_allclose(
        _lp(d, v), st.lognorm.logpdf(1.7, 0.8, scale=np.exp(0.5)),
        rtol=1e-4)
    s = d.rsample((8000,)).numpy()
    np.testing.assert_allclose(np.median(s), np.exp(0.5), rtol=0.1)


def test_transformed_distribution_event_rank_reduction():
    # base with batch (3,) pushed through an event-rank-1 transform:
    # log_prob must come back scalar (batch ()), not per-element
    base = D.Normal(paddle.to_tensor(np.zeros(3, "float32")),
                    paddle.to_tensor(np.ones(3, "float32")))
    d = D.TransformedDistribution(base, [D.StickBreakingTransform()])
    assert d.event_shape == (4,)
    y = d.sample(())
    lp = d.log_prob(y)
    assert tuple(lp.shape) == (), lp.shape
    # density check vs change of variables done by hand
    t = D.StickBreakingTransform()
    x = t.inverse(y)
    by_hand = float(base.log_prob(x).numpy().sum()) \
        - float(t.forward_log_det_jacobian(x))
    np.testing.assert_allclose(float(lp), by_hand, rtol=1e-5)


def test_chain_transform_mixed_event_ranks():
    chain = D.ChainTransform([D.ExpTransform(),
                              D.StickBreakingTransform()])
    x = paddle.to_tensor(np.array([0.2, -0.4, 0.1], "float32"))
    ldj = chain.forward_log_det_jacobian(x)
    assert tuple(ldj.shape) == (), ldj.shape
    # by hand: exp ldj summed over the event dim + stickbreak ldj
    e = D.ExpTransform()
    s = D.StickBreakingTransform()
    by_hand = float(e.forward_log_det_jacobian(x).numpy().sum()) + \
        float(s.forward_log_det_jacobian(e.forward(x)))
    np.testing.assert_allclose(float(ldj), by_hand, rtol=1e-5)


def test_poisson_entropy_large_rate():
    got = float(D.Poisson(paddle.to_tensor(500.0)).entropy())
    # exact: 0.5 log(2 pi e lam) - corrections ~ 4.5324
    np.testing.assert_allclose(
        got, 0.5 * np.log(2 * np.pi * np.e * 500.0) - 1 / 6000.0,
        rtol=1e-4)
