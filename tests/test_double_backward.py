"""Eager higher-order autograd (create_graph=True) vs torch oracle.

Reference capability: the 105 hand-written *_double_grad ops
(/root/reference/paddle/phi/ops/yaml/backward.yaml:4 abs_double_grad)
powering paddle.grad(..., create_graph=True) for GAN gradient
penalties, PINNs, etc.

TPU-native mechanism under test (autograd/__init__.py _replay_plan /
_grad_create_graph): the recorded subgraph is replayed as a pure jax
function; its vjp runs as ONE new tape op whose own jax.vjp supplies
the next derivative order — so every differentiable op gets
double-grad capability for free instead of needing a hand-written
double-grad kernel.
"""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def _t(a):
    return paddle.to_tensor(np.asarray(a, np.float32), stop_gradient=False)


def _tt(a):
    return torch.tensor(np.asarray(a, np.float32), requires_grad=True)


def _check_double(p_fn, t_fn, shapes, rtol=1e-4, seed=0):
    """d/dx sum(grad(sum(f(x...)), xi)^2) must match torch per input.

    Inputs whose second derivative is structurally zero come back as
    None under allow_unused on either side — compared as zeros.
    """
    rng = np.random.RandomState(seed)
    vals = [rng.randn(*s).astype(np.float32) for s in shapes]
    xs = [_t(v) for v in vals]
    y = p_fn(*xs).sum()
    gs = paddle.grad(y, xs, create_graph=True)
    loss2 = sum((g * g).sum() for g in gs)
    gs2 = paddle.grad(loss2, xs, allow_unused=True)

    xts = [_tt(v) for v in vals]
    yt = t_fn(*xts).sum()
    gts = torch.autograd.grad(yt, xts, create_graph=True)
    loss2t = sum((g * g).sum() for g in gts)
    if not loss2t.requires_grad:
        # first grad is constant (e.g. mean, maximum): the whole second
        # order is identically zero
        gts2 = [None] * len(xts)
    else:
        gts2 = torch.autograd.grad(loss2t, xts, allow_unused=True)
    for v, g, gt in zip(vals, gs2, gts2):
        a = np.zeros_like(v) if g is None else np.asarray(g._data)
        b = (np.zeros_like(v) if gt is None
             else gt.detach().numpy(force=True))
        np.testing.assert_allclose(a, b, rtol=rtol, atol=1e-5)


UNARY = [
    ("tanh", paddle.tanh, torch.tanh),
    ("sigmoid", F.sigmoid, torch.sigmoid),
    ("exp", paddle.exp, torch.exp),
    ("sin", paddle.sin, torch.sin),
    ("cos", paddle.cos, torch.cos),
    ("square", paddle.square, torch.square),
    ("softplus", F.softplus, torch.nn.functional.softplus),
    ("gelu", F.gelu, torch.nn.functional.gelu),
    ("silu", F.silu, torch.nn.functional.silu),
    ("abs", paddle.abs, torch.abs),
    ("rsqrt_shift",
     lambda x: paddle.rsqrt(x * x + 1.0),
     lambda x: torch.rsqrt(x * x + 1.0)),
    ("log_shift",
     lambda x: paddle.log(x * x + 1.0),
     lambda x: torch.log(x * x + 1.0)),
    ("sqrt_shift",
     lambda x: paddle.sqrt(x * x + 1.0),
     lambda x: torch.sqrt(x * x + 1.0)),
    ("logsumexp", paddle.logsumexp, torch.logsumexp_wrapper
     if hasattr(torch, "logsumexp_wrapper") else
     (lambda x: torch.logsumexp(x, dim=-1))),
    ("softmax", lambda x: F.softmax(x, axis=-1),
     lambda x: torch.softmax(x, dim=-1)),
    ("log_softmax", lambda x: F.log_softmax(x, axis=-1),
     lambda x: torch.log_softmax(x, dim=-1)),
    ("mean", paddle.mean, torch.mean),
    ("cumsum_tanh",
     lambda x: paddle.cumsum(paddle.tanh(x), axis=-1),
     lambda x: torch.cumsum(torch.tanh(x), dim=-1)),
]


@pytest.mark.parametrize("name,p_fn,t_fn", UNARY,
                         ids=[u[0] for u in UNARY])
def test_unary_double_grad(name, p_fn, t_fn):
    if name == "logsumexp":
        p = lambda x: paddle.logsumexp(x, axis=-1)      # noqa: E731
        _check_double(p, t_fn, [(3, 5)])
    else:
        _check_double(p_fn, t_fn, [(3, 5)])


BINARY = [
    ("matmul", paddle.matmul, torch.matmul, [(3, 4), (4, 2)]),
    ("mul", lambda a, b: a * b, lambda a, b: a * b, [(3, 4), (3, 4)]),
    ("div_shift",
     lambda a, b: a / (b * b + 1.0),
     lambda a, b: a / (b * b + 1.0), [(3, 4), (3, 4)]),
    ("pow3",
     lambda a, b: (a * a + b * b + 1.0) ** 3.0,
     lambda a, b: (a * a + b * b + 1.0) ** 3.0, [(3, 4), (3, 4)]),
    ("maximum", paddle.maximum, torch.maximum, [(3, 4), (3, 4)]),
    ("bmm", paddle.bmm, torch.bmm, [(2, 3, 4), (2, 4, 2)]),
]


@pytest.mark.parametrize("name,p_fn,t_fn,shapes", BINARY,
                         ids=[b[0] for b in BINARY])
def test_binary_double_grad(name, p_fn, t_fn, shapes):
    _check_double(p_fn, t_fn, shapes)


def test_conv2d_double_grad():
    _check_double(
        lambda x, w: F.conv2d(x, w, stride=1, padding=1),
        lambda x, w: torch.nn.functional.conv2d(x, w, stride=1,
                                                padding=1),
        [(2, 3, 8, 8), (4, 3, 3, 3)], rtol=1e-3)


def test_layer_norm_double_grad():
    def p(x, w, b):
        return F.layer_norm(x, normalized_shape=[6], weight=w, bias=b)

    def t(x, w, b):
        return torch.nn.functional.layer_norm(x, [6], w, b)

    _check_double(p, t, [(4, 6), (6,), (6,)], rtol=1e-3)


def test_triple_grad_quartic():
    x = _t([0.5, -1.5])
    y = (x ** 4.0).sum()
    (g1,) = paddle.grad(y, [x], create_graph=True)
    (g2,) = paddle.grad(g1.sum(), [x], create_graph=True)
    (g3,) = paddle.grad(g2.sum(), [x], create_graph=True)
    (g4,) = paddle.grad(g3.sum(), [x])
    np.testing.assert_allclose(np.asarray(g3._data),
                               24.0 * np.array([0.5, -1.5]), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g4._data), [24.0, 24.0],
                               rtol=1e-5)


def test_gradient_penalty_reaches_all_weights():
    """WGAN-GP shape: the second backward must reach the weights, not
    just the penalized input."""
    rng = np.random.RandomState(7)
    x = _t(rng.randn(4, 8))
    w1 = _t(rng.randn(8, 16))
    w2 = _t(rng.randn(16, 1))
    d = paddle.matmul(paddle.tanh(paddle.matmul(x, w1)), w2).sum()
    (gx,) = paddle.grad(d, [x], create_graph=True)
    gp = ((gx ** 2.0).sum() ** 0.5 - 1.0) ** 2.0
    gp.backward()

    xt, w1t, w2t = (_tt(np.asarray(v._data)) for v in (x, w1, w2))
    dt = (torch.tanh(xt @ w1t) @ w2t).sum()
    (gxt,) = torch.autograd.grad(dt, [xt], create_graph=True)
    gpt = ((gxt ** 2).sum() ** 0.5 - 1.0) ** 2
    gpt.backward()
    for p, t in ((w1, w1t), (w2, w2t), (x, xt)):
        np.testing.assert_allclose(np.asarray(p.grad._data),
                                   t.grad.numpy(), rtol=1e-4, atol=1e-6)


def test_grad_outputs_cotangent_linked():
    """A differentiable grad_outputs tensor stays on the tape."""
    x = _t([1.0, 2.0])
    c = _t([3.0, 4.0])
    y = x * x
    (g,) = paddle.grad(y, [x], grad_outputs=[c], create_graph=True)
    # g = 2 x c; d(sum g)/dc = 2x
    (gc,) = paddle.grad(g.sum(), [c])
    np.testing.assert_allclose(np.asarray(gc._data), [2.0, 4.0])


def test_intermediate_input_cut():
    """grad wrt an intermediate cuts the graph there; second order
    flows through the intermediate's producer."""
    x = _t([0.7, -0.3])
    h = paddle.tanh(x)
    y = (h * h).sum()
    (gh,) = paddle.grad(y, [h], create_graph=True)
    np.testing.assert_allclose(np.asarray(gh._data),
                               2 * np.tanh([0.7, -0.3]), rtol=1e-6)
    (gx,) = paddle.grad(gh.sum(), [x])
    # d(2 tanh x)/dx = 2 (1 - tanh^2)
    np.testing.assert_allclose(np.asarray(gx._data),
                               2 * (1 - np.tanh([0.7, -0.3]) ** 2),
                               rtol=1e-5)


def test_unused_input_raises_and_allow_unused():
    x = _t([1.0])
    z = _t([2.0])
    y = (x * x).sum()
    with pytest.raises(RuntimeError):
        paddle.grad(y, [x, z], create_graph=True)
    gx, gz = paddle.grad(y, [x, z], create_graph=True, allow_unused=True)
    assert gz is None
    np.testing.assert_allclose(np.asarray(gx._data), [2.0])


def test_duplicate_inputs_share_grad():
    x = _t([1.0, 2.0])
    y = (x ** 3.0).sum()
    g1, g2 = paddle.grad(y, [x, x], create_graph=True)
    np.testing.assert_allclose(np.asarray(g1._data), [3.0, 12.0])
    np.testing.assert_allclose(np.asarray(g2._data), [3.0, 12.0])


def test_pylayer_double_backward():
    """PyLayer supports create_graph=True when its backward is built
    from paddle ops (the reference's differentiable-backward contract
    for double grad): y = x^3 via a custom layer whose backward is
    3 x^2 g — second grad must be 6x."""
    class Cube(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x * x

        @staticmethod
        def backward(ctx, g):
            (x,) = ctx.saved_tensors()
            return g * 3.0 * x * x

    x = _t([2.0, -1.0])
    y = Cube.apply(x).sum()
    (g1,) = paddle.grad(y, [x], create_graph=True)
    np.testing.assert_allclose(np.asarray(g1._data), [12.0, 3.0])
    (g2,) = paddle.grad(g1.sum(), [x])
    np.testing.assert_allclose(np.asarray(g2._data), [12.0, -6.0])


def test_pylayer_double_backward_custom_grad_respected():
    """The replay must use the USER backward, not autodiff of the
    forward: a layer whose backward deliberately scales grads by 10."""
    class Scaled(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x

        @staticmethod
        def backward(ctx, g):
            (x,) = ctx.saved_tensors()
            return g * 2.0 * x * 10.0       # 10x the true grad

    x = _t([3.0])
    y = Scaled.apply(x).sum()
    (g1,) = paddle.grad(y, [x], create_graph=True)
    np.testing.assert_allclose(np.asarray(g1._data), [60.0])
    (g2,) = paddle.grad(g1.sum(), [x])
    np.testing.assert_allclose(np.asarray(g2._data), [20.0])


def test_pylayer_gradient_penalty_through_network():
    """A PyLayer inside a small net, WGAN-GP style second backward."""
    class LeakyAbs(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return paddle.abs(x)

        @staticmethod
        def backward(ctx, g):
            (x,) = ctx.saved_tensors()
            return g * paddle.tanh(10.0 * x)  # smooth custom sign

    rng = np.random.RandomState(3)
    x = _t(rng.randn(4, 6))
    w = _t(rng.randn(6, 1))
    d = LeakyAbs.apply(paddle.matmul(x, w)).sum()
    (gx,) = paddle.grad(d, [x], create_graph=True)
    gp = (gx ** 2.0).sum()
    gp.backward()
    assert w.grad is not None and np.isfinite(w.grad.numpy()).all()


def test_released_graph_errors_clearly():
    x = _t([1.0])
    y = (x * x).sum()
    y.backward()          # consumes the tape
    with pytest.raises(RuntimeError, match="released"):
        paddle.grad(y, [x], create_graph=True)


def test_first_order_unchanged_without_create_graph():
    x = _t([1.0, 2.0])
    y = (x * x).sum()
    (g,) = paddle.grad(y, [x])
    assert g.stop_gradient
    np.testing.assert_allclose(np.asarray(g._data), [2.0, 4.0])
