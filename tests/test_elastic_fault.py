"""Elastic membership + fault-handling tests (reference mechanism:
ElasticManager heartbeats in etcd, node-leave detection, relaunch;
tests kill members and assert the survivors notice — the reference
does this ad hoc by killing subprocesses)."""
import time

import pytest

from paddle_tpu.distributed.elastic import ElasticManager, FileKVStore


def test_membership_join_and_leave(tmp_path):
    store = FileKVStore(str(tmp_path))
    changes = []
    m0 = ElasticManager(store, "job", rank=0, heartbeat_s=0.1,
                        ttl_s=0.5, on_change=changes.append).start()
    m1 = ElasticManager(store, "job", rank=1, heartbeat_s=0.1,
                        ttl_s=0.5).start()
    # wait until m0's WATCHER has observed the join (not just the
    # store) — stopping m1 earlier would race the first watch tick
    deadline = time.time() + 5
    while time.time() < deadline and [0, 1] not in changes:
        time.sleep(0.05)
    assert [0, 1] in changes, changes
    assert m0.world() == [0, 1]

    # node 1 dies (stop heartbeating); TTL expiry -> leave detected
    m1.stop()
    deadline = time.time() + 5
    while time.time() < deadline and not any(w == [0] for w in changes):
        time.sleep(0.05)
    assert any(w == [0] for w in changes), changes
    m0.stop()


def test_scale_out_triggers_on_change(tmp_path):
    store = FileKVStore(str(tmp_path))
    changes = []
    m0 = ElasticManager(store, "job2", rank=0, heartbeat_s=0.1,
                        ttl_s=1.0, on_change=changes.append).start()
    time.sleep(0.3)
    m2 = ElasticManager(store, "job2", rank=2, heartbeat_s=0.1,
                        ttl_s=1.0).start()
    deadline = time.time() + 5
    while time.time() < deadline and not any(
            w == [0, 2] for w in changes):
        time.sleep(0.05)
    assert any(w == [0, 2] for w in changes), changes
    m0.stop()
    m2.stop()


def test_launcher_kills_job_on_worker_failure(tmp_path):
    """The launcher's failure policy (reference launch controllers):
    one worker exiting nonzero terminates the whole job with its
    code."""
    import subprocess
    import sys
    import os

    script = tmp_path / "failer.py"
    script.write_text(
        "import os, sys, time\n"
        "rank = int(os.environ['PADDLE_TRAINER_ID'])\n"
        "if rank == 1:\n"
        "    sys.exit(3)\n"
        "time.sleep(300)\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo"
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", str(script)],
        env=env, capture_output=True, timeout=240)
    # job fails fast with the worker's code, not after the 300s sleep;
    # generous margin — under pytest -n 8 process startup is slow
    assert proc.returncode == 3, (proc.returncode, proc.stderr[-500:])
    assert time.time() - t0 < 120


def test_launcher_relaunches_after_midrun_kill(tmp_path):
    """Fault injection (reference: ElasticManager relaunch): a worker
    is SIGKILLed mid-run on the first attempt; with --max_restarts the
    launcher respawns the whole job with PADDLE_RESTART_COUNT bumped,
    and the second attempt completes cleanly."""
    import os
    import signal as _signal
    import subprocess
    import sys

    script = tmp_path / "crasher.py"
    script.write_text(
        "import os, signal, time\n"
        "rank = int(os.environ['PADDLE_TRAINER_ID'])\n"
        "attempt = int(os.environ['PADDLE_RESTART_COUNT'])\n"
        "open(f'%s/seen.{rank}.{attempt}', 'w').close()\n"
        "if rank == 1 and attempt == 0:\n"
        "    time.sleep(0.3)  # die mid-run, not at startup\n"
        "    os.kill(os.getpid(), signal.SIGKILL)\n"
        "time.sleep(0.5)\n" % tmp_path)
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo"
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--max_restarts", "2", str(script)],
        env=env, capture_output=True, timeout=30)
    assert proc.returncode == 0, (proc.returncode, proc.stderr[-800:])
    assert b"relaunching job (attempt 1/2)" in proc.stderr
    # both attempts ran both ranks; attempt 1 finished for rank 1
    for marker in ("seen.0.0", "seen.1.0", "seen.0.1", "seen.1.1"):
        assert (tmp_path / marker).exists(), marker


def test_launcher_exhausts_restarts(tmp_path):
    """A deterministic failure stops after max_restarts attempts and
    propagates the worker's exit code."""
    import os
    import subprocess
    import sys

    script = tmp_path / "alwaysfail.py"
    script.write_text(
        "import os, sys\n"
        "attempt = int(os.environ['PADDLE_RESTART_COUNT'])\n"
        "open(f'%s/try.{os.environ[\"PADDLE_TRAINER_ID\"]}.{attempt}',"
        " 'w').close()\n"
        "sys.exit(7)\n" % tmp_path)
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo"
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--max_restarts", "1", str(script)],
        env=env, capture_output=True, timeout=30)
    assert proc.returncode == 7, (proc.returncode, proc.stderr[-500:])
    assert (tmp_path / "try.0.0").exists()
    assert (tmp_path / "try.0.1").exists()
    assert not (tmp_path / "try.0.2").exists()
