"""Elastic membership + fault-handling tests (reference mechanism:
ElasticManager heartbeats in etcd, node-leave detection, relaunch;
tests kill members and assert the survivors notice — the reference
does this ad hoc by killing subprocesses)."""
import time

import pytest

from paddle_tpu.distributed.elastic import ElasticManager, FileKVStore


def test_membership_join_and_leave(tmp_path):
    store = FileKVStore(str(tmp_path))
    changes = []
    m0 = ElasticManager(store, "job", rank=0, heartbeat_s=0.1,
                        ttl_s=0.5, on_change=changes.append).start()
    m1 = ElasticManager(store, "job", rank=1, heartbeat_s=0.1,
                        ttl_s=0.5).start()
    # wait until m0's WATCHER has observed the join (not just the
    # store) — stopping m1 earlier would race the first watch tick
    deadline = time.time() + 5
    while time.time() < deadline and [0, 1] not in changes:
        time.sleep(0.05)
    assert [0, 1] in changes, changes
    assert m0.world() == [0, 1]

    # node 1 dies (stop heartbeating); TTL expiry -> leave detected
    m1.stop()
    deadline = time.time() + 5
    while time.time() < deadline and not any(w == [0] for w in changes):
        time.sleep(0.05)
    assert any(w == [0] for w in changes), changes
    m0.stop()


def test_scale_out_triggers_on_change(tmp_path):
    store = FileKVStore(str(tmp_path))
    changes = []
    m0 = ElasticManager(store, "job2", rank=0, heartbeat_s=0.1,
                        ttl_s=1.0, on_change=changes.append).start()
    time.sleep(0.3)
    m2 = ElasticManager(store, "job2", rank=2, heartbeat_s=0.1,
                        ttl_s=1.0).start()
    deadline = time.time() + 5
    while time.time() < deadline and not any(
            w == [0, 2] for w in changes):
        time.sleep(0.05)
    assert any(w == [0, 2] for w in changes), changes
    m0.stop()
    m2.stop()


def test_filekvstore_gc_purges_expired_entries(tmp_path):
    """ISSUE 14 satellite: TTL-expired entries are PHYSICALLY deleted
    during get_prefix (lazy GC) — a long-running job's store must not
    grow unboundedly with dead nodes' files. Unexpired and foreign
    (non-TTL-wrapped) files are left alone."""
    store = FileKVStore(str(tmp_path))
    store.put("elastic/job/nodes/0", "alive", ttl_s=60.0)
    store.put("elastic/job/nodes/1", "dead", ttl_s=0.01)
    store.put("elastic/job/nodes/2", "dead2", ttl_s=0.01)
    # a foreign file under the prefix: malformed, must survive GC
    foreign = tmp_path / "elastic__job__nodes__raw"
    foreign.write_text("not-a-ttl-payload")
    time.sleep(0.05)
    out = store.get_prefix("elastic/job/nodes/")
    assert out == {"elastic/job/nodes/0": "alive"}
    names = sorted(p.name for p in tmp_path.iterdir())
    assert "elastic__job__nodes__1" not in names, names
    assert "elastic__job__nodes__2" not in names, names
    assert "elastic__job__nodes__0" in names
    assert "elastic__job__nodes__raw" in names  # foreign file kept


def test_kvstore_ttl_semantics_parity_file_vs_tcp(tmp_path):
    """ISSUE 15 satellite: FileKVStore and TCPKVStore must expire keys
    IDENTICALLY — read-side TTL from the same payload stamp, lazy
    physical GC of well-formed expired entries, re-put after expiry
    visible again, and delete of a missing key a no-op. The TCP store
    rode untested for TTL until now (its expired entries also used to
    pile up server-side forever; get_prefix now GCs them like the file
    store does)."""
    import socket

    from paddle_tpu import native
    from paddle_tpu.distributed.elastic import TCPKVStore

    if native.get_lib() is None:
        pytest.skip("native library unavailable")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    stores = {"file": FileKVStore(str(tmp_path)),
              "tcp": TCPKVStore("127.0.0.1", port, is_master=True)}
    for st in stores.values():
        st.put("par/keep", "v", ttl_s=60.0)
        st.put("par/gone", "v", ttl_s=0.05)
        st.put("par/forever", "v")
    time.sleep(0.1)
    views = {name: st.get_prefix("par/") for name, st in stores.items()}
    assert views["file"] == views["tcp"] == \
        {"par/keep": "v", "par/forever": "v"}
    # expired entries were physically GC'd by the read, in BOTH stores
    assert "par__gone" not in {p.name for p in tmp_path.iterdir()}
    assert "par/gone" not in stores["tcp"]._store.list("par/")
    # a re-put of an expired key becomes visible again
    for name, st in stores.items():
        st.put("par/gone", "v2", ttl_s=60.0)
        assert st.get_prefix("par/").get("par/gone") == "v2", name
    # delete parity, including deleting a key that never existed
    for name, st in stores.items():
        st.delete("par/keep")
        st.delete("par/never-existed")
        assert "par/keep" not in st.get_prefix("par/"), name


def test_launcher_kills_job_on_worker_failure(tmp_path):
    """The launcher's failure policy (reference launch controllers):
    one worker exiting nonzero terminates the whole job with its
    code."""
    import subprocess
    import sys
    import os

    script = tmp_path / "failer.py"
    script.write_text(
        "import os, sys, time\n"
        "rank = int(os.environ['PADDLE_TRAINER_ID'])\n"
        "if rank == 1:\n"
        "    sys.exit(3)\n"
        "time.sleep(300)\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo"
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", str(script)],
        env=env, capture_output=True, timeout=240)
    # job fails fast with the worker's code, not after the 300s sleep;
    # generous margin — under pytest -n 8 process startup is slow
    assert proc.returncode == 3, (proc.returncode, proc.stderr[-500:])
    assert time.time() - t0 < 120


def test_launcher_relaunches_after_midrun_kill(tmp_path):
    """Fault injection (reference: ElasticManager relaunch): a worker
    is SIGKILLed mid-run on the first attempt; with --max_restarts the
    launcher respawns the whole job with PADDLE_RESTART_COUNT bumped,
    and the second attempt completes cleanly."""
    import os
    import signal as _signal
    import subprocess
    import sys

    script = tmp_path / "crasher.py"
    script.write_text(
        "import os, signal, time\n"
        "rank = int(os.environ['PADDLE_TRAINER_ID'])\n"
        "attempt = int(os.environ['PADDLE_RESTART_COUNT'])\n"
        "open(f'%s/seen.{rank}.{attempt}', 'w').close()\n"
        "if rank == 1 and attempt == 0:\n"
        "    time.sleep(0.3)  # die mid-run, not at startup\n"
        "    os.kill(os.getpid(), signal.SIGKILL)\n"
        "time.sleep(0.5)\n" % tmp_path)
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo"
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--max_restarts", "2", str(script)],
        env=env, capture_output=True, timeout=30)
    assert proc.returncode == 0, (proc.returncode, proc.stderr[-800:])
    assert b"relaunching job (attempt 1/2)" in proc.stderr
    # both attempts ran both ranks; attempt 1 finished for rank 1
    for marker in ("seen.0.0", "seen.1.0", "seen.0.1", "seen.1.1"):
        assert (tmp_path / marker).exists(), marker


def test_launcher_exhausts_restarts(tmp_path):
    """A deterministic failure stops after max_restarts attempts and
    propagates the worker's exit code."""
    import os
    import subprocess
    import sys

    script = tmp_path / "alwaysfail.py"
    script.write_text(
        "import os, sys\n"
        "attempt = int(os.environ['PADDLE_RESTART_COUNT'])\n"
        "open(f'%s/try.{os.environ[\"PADDLE_TRAINER_ID\"]}.{attempt}',"
        " 'w').close()\n"
        "sys.exit(7)\n" % tmp_path)
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo"
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--max_restarts", "1", str(script)],
        env=env, capture_output=True, timeout=30)
    assert proc.returncode == 7, (proc.returncode, proc.stderr[-500:])
    assert (tmp_path / "try.0.0").exists()
    assert (tmp_path / "try.0.1").exists()
    assert not (tmp_path / "try.0.2").exists()


def test_elastic_scale_in_resumes_from_checkpoint(tmp_path):
    """End-to-end elastic scale-in (VERDICT r2 item 7, reference
    ElasticManager manager.py:125): 3 workers train; worker 2 dies
    mid-run; the launcher relaunches at the surviving world size n=2;
    workers resume from the LATEST COMMITTED distributed checkpoint
    (per-step dirs; `_COMMITTED.json` written last, so a worker killed
    mid-save leaves an ignorable uncommitted dir) and the final params
    match an uninterrupted oracle run exactly."""
    import json
    import os
    import subprocess
    import sys

    ck = tmp_path / "ckpt"
    ck.mkdir()
    script = tmp_path / "elastic_train.py"
    script.write_text("""
import json, os, signal, sys, time
sys.path.insert(0, "/root/repo")
from paddle_tpu._testing import force_cpu
force_cpu(1)
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import checkpoint as dc

rank = int(os.environ["PADDLE_TRAINER_ID"])
world = int(os.environ["PADDLE_TRAINERS_NUM"])
attempt = int(os.environ["PADDLE_RESTART_COUNT"])
CK = os.environ["CKPT_DIR"]
TOTAL = 8
open(os.path.join(CK, f"world.{attempt}.{rank}.{world}"), "w").close()

paddle.seed(0)
m = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
opt = paddle.optimizer.SGD(0.1, parameters=m.parameters())
x = paddle.to_tensor(np.random.RandomState(0).randn(16, 4).astype("f4"))
y = paddle.to_tensor(np.random.RandomState(1).randn(16, 2).astype("f4"))
loss_fn = nn.MSELoss()

state = {"model": m.state_dict(), "step": -1}
start = 0
latest = dc.latest_committed(CK)
if latest is not None:
    dc.load_state_dict(state, latest)
    start = state["step"] + 1

def ck_step():
    d = dc.latest_committed(CK)
    if d is None:
        return -1
    try:
        with open(os.path.join(d, "metadata.json")) as f:
            return json.load(f)["tensors"]["step"]["value"]
    except Exception:
        return -1

def barrier(step):
    # ranks free-run otherwise; real training syncs per step through
    # collectives, emulated here with marker files
    open(os.path.join(CK, f"sync.{attempt}.{step}.{rank}"), "w").close()
    while not all(os.path.exists(os.path.join(
            CK, f"sync.{attempt}.{step}.{r}")) for r in range(world)):
        time.sleep(0.02)

for step in range(start, TOTAL):
    barrier(step)
    loss = loss_fn(m(x), y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    if rank == 0:
        dc.save_state_dict({"model": m.state_dict(), "step": step},
                           os.path.join(CK, "step_%04d" % step))
    if rank == 2 and attempt == 0 and step >= 3:
        while ck_step() < 3:
            time.sleep(0.05)
        os.kill(os.getpid(), signal.SIGKILL)
if rank == 0:
    with open(os.path.join(CK, "final_loss"), "w") as f:
        f.write(str(float(loss)))
""")
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo"
    env["CKPT_DIR"] = str(ck)
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "3", "--max_restarts", "2",
         "--np_range", "2:3", str(script)],
        env=env, capture_output=True, timeout=240)
    assert proc.returncode == 0, (proc.returncode, proc.stderr[-800:])
    assert b"scaling 3 -> 2 workers" in proc.stderr

    # attempt 0 ran 3 workers; attempt 1 ran at world size 2
    seen = sorted(p.name for p in ck.glob("world.*"))
    assert "world.0.0.3" in seen and "world.1.0.2" in seen, seen
    assert "world.1.1.2" in seen and not any(
        n.startswith("world.1.2") for n in seen), seen

    # resumed training completed and matches the uninterrupted oracle
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed import checkpoint as dc
    paddle.seed(0)
    oracle = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    opt = paddle.optimizer.SGD(0.1, parameters=oracle.parameters())
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(16, 4).astype("f4"))
    y = paddle.to_tensor(
        np.random.RandomState(1).randn(16, 2).astype("f4"))
    loss_fn = nn.MSELoss()
    for _ in range(8):
        loss = loss_fn(oracle(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    final_loss = float((ck / "final_loss").read_text())
    assert abs(final_loss - float(loss)) < 1e-5, (final_loss,
                                                  float(loss))
    paddle.seed(0)
    fresh = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    state = {"model": fresh.state_dict(), "step": -1}
    latest = dc.latest_committed(str(ck))
    assert latest is not None and latest.endswith("step_0007"), latest
    dc.load_state_dict(state, latest)
    assert state["step"] == 7
    for (_, a), (_, b) in zip(fresh.named_parameters(),
                              oracle.named_parameters()):
        np.testing.assert_allclose(a.numpy(), b.numpy(), rtol=1e-6,
                                   atol=1e-6)


@pytest.mark.parametrize("store_kind", ["file", "tcp"])
def test_elastic_scale_out_resumes_from_checkpoint(tmp_path,
                                                   store_kind):
    """End-to-end elastic scale-OUT (the mirror of the scale-in e2e;
    reference ElasticManager manager.py:125 handles both directions):
    2 workers train; a third announces itself through the elastic
    store's join/ prefix; the launcher restarts the job at n=3; workers
    resume from the distributed checkpoint and the final params match
    an uninterrupted oracle run exactly.

    store_kind="tcp" (round 5) runs the same e2e over the native
    TCPStore (store.cc) hosted by the launcher — the no-shared-
    filesystem multi-host deployment shape — with the joiner
    announcing itself through a TCPKVStore client."""
    import os
    import pathlib
    import socket as _socket
    import subprocess
    import sys
    import json as _json

    from paddle_tpu.distributed.elastic import FileKVStore, TCPKVStore

    ck = tmp_path / "ckpt"
    ck.mkdir()
    store_dir = tmp_path / "store"
    if store_kind == "tcp":
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        tcp_port = s.getsockname()[1]
        s.close()
        store_url = f"tcp://127.0.0.1:{tcp_port}"

        def join_client():
            return TCPKVStore("127.0.0.1", tcp_port, is_master=False)
    else:
        store_url = str(store_dir)

        def join_client():
            return FileKVStore(str(store_dir))
    script = tmp_path / "elastic_out_train.py"
    script.write_text("""
import json, os, sys, time
sys.path.insert(0, "/root/repo")
from paddle_tpu._testing import force_cpu
force_cpu(1)
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import checkpoint as dc

rank = int(os.environ["PADDLE_TRAINER_ID"])
world = int(os.environ["PADDLE_TRAINERS_NUM"])
attempt = int(os.environ["PADDLE_RESTART_COUNT"])
CK = os.environ["CKPT_DIR"]
TOTAL = 8
open(os.path.join(CK, f"world.{attempt}.{rank}.{world}"), "w").close()

paddle.seed(0)
m = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
opt = paddle.optimizer.SGD(0.1, parameters=m.parameters())
x = paddle.to_tensor(np.random.RandomState(0).randn(16, 4).astype("f4"))
y = paddle.to_tensor(np.random.RandomState(1).randn(16, 2).astype("f4"))
loss_fn = nn.MSELoss()

state = {"model": m.state_dict(), "step": -1}
start = 0
latest = dc.latest_committed(CK)
if latest is not None:
    dc.load_state_dict(state, latest)
    start = state["step"] + 1

def barrier(step):
    open(os.path.join(CK, f"sync.{attempt}.{step}.{rank}"), "w").close()
    while not all(os.path.exists(os.path.join(
            CK, f"sync.{attempt}.{step}.{r}")) for r in range(world)):
        time.sleep(0.02)

for step in range(start, TOTAL):
    barrier(step)
    loss = loss_fn(m(x), y)
    loss.backward()
    opt.step()
    opt.clear_grad()
    if rank == 0:
        dc.save_state_dict({"model": m.state_dict(), "step": step},
                           os.path.join(CK, "step_%04d" % step))
    if attempt == 0:
        # attempt 0 paces itself so the join lands mid-run (the
        # launcher's SIGTERM interrupts this sleep)
        time.sleep(0.3)
if rank == 0:
    with open(os.path.join(CK, "final_loss"), "w") as f:
        f.write(str(float(loss)))
""")
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo"
    env["CKPT_DIR"] = str(ck)
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--max_restarts", "0",
         "--np_range", "2:3", "--elastic_store", store_url,
         str(script)],
        env=env, stderr=subprocess.PIPE)
    try:
        # wait for training to make some COMMITTED checkpoint progress
        deadline = time.time() + 120
        from paddle_tpu.distributed import checkpoint as _dc

        def ck_step():
            d = _dc.latest_committed(str(ck))
            if d is None:
                return -1
            try:
                return _json.loads(
                    (pathlib.Path(d) / "metadata.json").read_text())[
                        "tensors"]["step"]["value"]
            except Exception:
                return -1
        while time.time() < deadline and ck_step() < 2:
            time.sleep(0.1)
        assert ck_step() >= 2, "attempt 0 never reached step 2"
        # ...then a new worker announces itself
        join_client().put("join/worker-new", "1")
        _, err = proc.communicate(timeout=180)
    except Exception:
        proc.kill()
        raise
    assert proc.returncode == 0, (proc.returncode, err[-800:])
    assert b"scaling 2 -> 3 workers (join)" in err

    # attempt 0 ran at world 2; attempt 1 at world 3, all three ranks
    seen = sorted(p.name for p in ck.glob("world.*"))
    assert "world.0.0.2" in seen and "world.0.1.2" in seen, seen
    for r in range(3):
        assert f"world.1.{r}.3" in seen, seen

    # resumed training completed and matches the uninterrupted oracle
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed import checkpoint as dc
    paddle.seed(0)
    oracle = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    opt = paddle.optimizer.SGD(0.1, parameters=oracle.parameters())
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(16, 4).astype("f4"))
    y = paddle.to_tensor(
        np.random.RandomState(1).randn(16, 2).astype("f4"))
    loss_fn = nn.MSELoss()
    for _ in range(8):
        loss = loss_fn(oracle(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    final_loss = float((ck / "final_loss").read_text())
    assert abs(final_loss - float(loss)) < 1e-5, (final_loss,
                                                  float(loss))
    paddle.seed(0)
    fresh = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    state = {"model": fresh.state_dict(), "step": -1}
    latest = dc.latest_committed(str(ck))
    assert latest is not None and latest.endswith("step_0007"), latest
    dc.load_state_dict(state, latest)
    assert state["step"] == 7
    for (_, a), (_, b) in zip(fresh.named_parameters(),
                              oracle.named_parameters()):
        np.testing.assert_allclose(a.numpy(), b.numpy(), rtol=1e-6,
                                   atol=1e-6)
