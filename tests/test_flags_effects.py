"""Every debugging/determinism flag has a REAL effect (VERDICT r2 item 9
— the actionable subset of the reference's 178 flags, flags.cc)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.flags import get_flag, set_flags


@pytest.fixture(autouse=True)
def _restore_flags():
    from paddle_tpu.core import flags as F
    saved = {n: f.value for n, f in F._REGISTRY.items()}
    yield
    for n, v in saved.items():
        F._REGISTRY[n].value = v


def test_flag_count_meets_bar():
    from paddle_tpu.core import flags as F
    assert len(F._REGISTRY) >= 25, sorted(F._REGISTRY)


def test_op_log_and_filter(capsys):
    set_flags({"FLAGS_op_log": True, "FLAGS_op_log_filter": "matmul"})
    a = paddle.randn([2, 3])
    paddle.matmul(a, paddle.randn([3, 4]))
    paddle.add(a, a)
    err = capsys.readouterr().err
    assert "[op] matmul" in err
    assert "[op] add" not in err


def test_call_stack_level_wraps_op_errors():
    set_flags({"FLAGS_call_stack_level": 2})
    with pytest.raises(RuntimeError, match="op 'matmul'.*inputs"):
        paddle.matmul(paddle.randn([2, 3]), paddle.randn([4, 5]))
    set_flags({"FLAGS_call_stack_level": 1})
    with pytest.raises(Exception) as e:
        paddle.matmul(paddle.randn([2, 3]), paddle.randn([4, 5]))
    assert not str(e.value).startswith("op ")


def test_nan_inf_dump_dir(tmp_path):
    set_flags({"FLAGS_check_nan_inf": True,
               "FLAGS_nan_inf_dump_dir": str(tmp_path)})
    x = paddle.to_tensor(np.array([1.0, np.inf], np.float32))
    with pytest.raises(FloatingPointError, match="dumped"):
        paddle.add(x, x)
    dumps = list(tmp_path.glob("naninf_add_*.npz"))
    assert dumps
    data = np.load(dumps[0])
    assert not np.isfinite(data["out0"]).all()


def test_deterministic_disables_attn_autotune():
    from paddle_tpu.ops.pallas import autotune
    import jax.numpy as jnp
    q = jnp.zeros((1, 8, 2, 4))
    set_flags({"FLAGS_deterministic": False})
    # (may still be None off-TPU; just must not crash)
    autotune.decide(q, q, True)
    set_flags({"FLAGS_deterministic": True})
    assert autotune.decide(q, q, True) is None


def test_matmul_precision_flag_updates_jax_config():
    import jax
    set_flags({"FLAGS_matmul_precision": "highest"})
    assert jax.config.jax_default_matmul_precision == "highest"
    set_flags({"FLAGS_matmul_precision": "default"})
    assert jax.config.jax_default_matmul_precision == "default"


def test_collective_debug_logs(capsys):
    from paddle_tpu.distributed import collective as C
    set_flags({"FLAGS_collective_debug": True})
    t = paddle.randn([4])
    C.all_reduce(t)
    C.broadcast(t, src=0)
    err = capsys.readouterr().err
    assert "[collective] all_reduce" in err
    assert "[collective] broadcast" in err


def test_retain_grad_for_all():
    set_flags({"FLAGS_retain_grad_for_all": True})
    x = paddle.randn([3])
    x.stop_gradient = False
    mid = x * 2.0
    out = paddle.sum(mid * mid)
    out.backward()
    assert mid.grad is not None
    np.testing.assert_allclose(mid.grad.numpy(), 2 * (2 * x.numpy()),
                               rtol=1e-6)
    set_flags({"FLAGS_retain_grad_for_all": False})
    y = paddle.randn([3])
    y.stop_gradient = False
    mid2 = y * 2.0
    paddle.sum(mid2 * mid2).backward()
    assert mid2.grad is None


def test_tensor_print_flags():
    set_flags({"FLAGS_tensor_print_precision": 2,
               "FLAGS_tensor_print_threshold": 5})
    t = paddle.to_tensor(np.array([1.23456789] * 10, np.float32))
    r = repr(t)
    assert "1.23," in r or "1.23 " in r or "1.23]" in r
    assert "..." in r          # summarized beyond threshold


def test_memory_stats_dump(tmp_path):
    import json
    path = str(tmp_path / "mem.json")
    set_flags({"FLAGS_memory_stats_dump_path": path})
    paddle.randn([64, 64]).numpy()       # touch the device
    stats = paddle.device.dump_memory_stats()
    assert os.path.exists(path)
    with open(path) as f:
        on_disk = json.load(f)
    assert set(stats) == set(on_disk)


def test_low_precision_op_list():
    from paddle_tpu.amp import debugging as amp_dbg
    set_flags({"FLAGS_low_precision_op_list": True})
    amp_dbg.clear_low_precision_op_list()
    with paddle.amp.auto_cast(level="O1"):
        paddle.matmul(paddle.randn([4, 4]), paddle.randn([4, 4]))
    ops = amp_dbg.get_low_precision_op_list()
    assert any(k.startswith("matmul->") for k in ops), ops


def test_max_specializations_flag_caps_jit():
    set_flags({"FLAGS_max_specializations": 2})
    calls = []

    @paddle.jit.to_static
    def f(x, n):
        calls.append(1)
        if float(paddle.sum(x)) > n:      # value-dependent guard
            return x + 1
        return x - 1

    for n in range(6):
        f(paddle.to_tensor(np.full((2,), float(n), np.float32)), 0.5)
    # capped: beyond 2 specializations the fn deopts to eager instead
    # of compiling forever — just assert it kept working
    assert len(calls) >= 2


def test_print_jaxpr_flag(capsys):
    set_flags({"FLAGS_print_jaxpr": True})

    @paddle.jit.to_static
    def g(x):
        return x * 2.0

    g(paddle.randn([2]))
    err = capsys.readouterr().err
    assert "lambda" in err or "jaxpr" in err.lower() or "mul" in err


def test_allocator_strategy_mapping():
    from paddle_tpu.core.flags import _allocator_env
    assert _allocator_env("auto_growth") == "default"
    assert _allocator_env("naive_best_fit") == "platform"
    set_flags({"FLAGS_allocator_strategy": "naive_best_fit"})
    assert os.environ["XLA_PYTHON_CLIENT_ALLOCATOR"] == "platform"
    set_flags({"FLAGS_allocator_strategy": "auto_growth"})


def test_watchdog_names_straggler_rank(tmp_path):
    """Timeout dump attribution (reference comm_task_manager): the rank
    whose heartbeat went stale is named."""
    import json
    import time
    from paddle_tpu.distributed.elastic import FileKVStore
    from paddle_tpu.distributed.watchdog import CollectiveWatchdog

    store = FileKVStore(str(tmp_path))
    # rank 1 published once, long ago (stalled); rank 0 and 2 are fresh
    now = time.time()
    store.put("watchdog/job/1", json.dumps({"ts": now - 300, "ops": 5}))
    store.put("watchdog/job/0", json.dumps({"ts": now, "ops": 50}))
    store.put("watchdog/job/2", json.dumps({"ts": now, "ops": 49}))
    wd = CollectiveWatchdog(timeout_s=5.0, interval_s=1.0, store=store,
                            job_id="job", rank=0, world_size=4)
    try:
        # rank 1 is stale, rank 3 never published: both named
        assert wd.find_stragglers() == [1, 3]
        import io
        import contextlib
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            wd._dump()
        out = buf.getvalue()
        assert "straggler rank(s): [1, 3]" in out
        assert "rank 1: ops=5" in out
    finally:
        wd.stop()


def test_watchdog_interval_from_flag():
    from paddle_tpu.distributed.watchdog import CollectiveWatchdog
    set_flags({"FLAGS_watchdog_interval_s": 3.5})
    wd = CollectiveWatchdog(timeout_s=1.0)
    assert wd.interval_s == 3.5
    wd.stop()


def test_kv_capacity_check_flag_disables_guard():
    from paddle_tpu.inference.decode import (init_static_cache,
                                             cache_attention)
    cache = init_static_cache(1, 4, 2, 8)
    cache = cache._replace(length=paddle.to_tensor(
        np.array([4], np.int32)))
    q = paddle.randn([1, 1, 2, 8])
    with pytest.raises(ValueError):
        cache_attention(q, q, q, cache)
    set_flags({"FLAGS_kv_capacity_check": False})
    out, _ = cache_attention(q, q, q, cache)   # clamped, not raised
    assert out.shape == [1, 1, 2, 8]
