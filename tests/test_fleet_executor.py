"""Fleet-executor actor runtime tests (reference:
test/cpp/fluid/fleet_executor + compute_interceptor_run_op_test.cc —
micro-batch DAG with credit-based flow control)."""
import numpy as np

from paddle_tpu.distributed.fleet_executor import (
    Carrier, ComputeInterceptor, FleetExecutor, InterceptorMessage,
    MessageBus, TaskNode,
)


def test_linear_pipeline_runs_all_microbatches():
    """3-stage chain, 4 micro-batches, buffer credit 2 (the reference's
    compute-interceptor ping-pong)."""
    n_micro = 4
    log = []

    def stage(name):
        def program(step, inputs):
            log.append((name, step))
            val = list(inputs.values())[0] if inputs else step
            return val if val is None else (val if name == "a"
                                            else val + 1)
        return program

    a = TaskNode(task_id=0, max_run_times=n_micro, program=stage("a"))
    b = TaskNode(task_id=1, max_run_times=n_micro, program=stage("b"))
    c = TaskNode(task_id=2, max_run_times=n_micro, program=stage("c"))
    a.add_downstream_task(1, 2)
    b.add_upstream_task(0, 2)
    b.add_downstream_task(2, 2)
    c.add_upstream_task(1, 2)

    ex = FleetExecutor()
    ex.init(0, [a, b, c])
    ex.run(timeout=30)
    ex.stop()
    for name in "abc":
        steps = [s for n, s in log if n == name]
        assert steps == list(range(n_micro)), (name, steps)
    # flow control: b's step k only after a's step k
    for k in range(n_micro):
        assert log.index(("a", k)) < log.index(("b", k)) < \
            log.index(("c", k))


def test_payloads_flow_downstream():
    results = []

    def src(step, inputs):
        return step * 10

    def sink(step, inputs):
        results.append(list(inputs.values())[0])
        return None

    a = TaskNode(task_id=0, max_run_times=3, program=src)
    b = TaskNode(task_id=1, max_run_times=3, program=sink)
    a.add_downstream_task(1, 1)
    b.add_upstream_task(0, 1)
    ex = FleetExecutor()
    ex.init(0, [a, b])
    ex.run(timeout=30)
    ex.stop()
    assert results == [0, 10, 20]


def test_cross_carrier_message_bus():
    """Two carriers (ranks) exchanging through the bus — the
    single-host model of the reference's multi-rank brpc bus."""
    results = []

    def src(step, inputs):
        return np.float32(step + 0.5)

    def sink(step, inputs):
        results.append(float(list(inputs.values())[0]))

    a = TaskNode(rank=0, task_id=0, max_run_times=2, program=src)
    b = TaskNode(rank=1, task_id=1, max_run_times=2, program=sink)
    a.add_downstream_task(1, 1)
    b.add_upstream_task(0, 1)
    ex = FleetExecutor()
    ex.init(0, [a])
    ex.init(1, [b])
    ex.run(timeout=30)
    ex.stop()
    assert results == [0.5, 1.5]


def test_error_propagates():
    def bad(step, inputs):
        raise ValueError("boom")

    a = TaskNode(task_id=0, max_run_times=1, program=bad)
    ex = FleetExecutor()
    ex.init(0, [a])
    try:
        ex.run(timeout=10)
        raised = False
    except ValueError:
        raised = True
    ex.stop()
    assert raised
