"""Fleet-side pipeline training: PipelineParallel.train_batch with
DistributedStrategy.pipeline_configs["schedule_mode"] (round 5).

Reference: fleet/meta_parallel/pipeline_parallel.py train_batch (:547)
driven by distributed_strategy pipeline configs — the fleet facade's
manual-pp user API, here sharing the auto-parallel partitioner's
compiled executor (one pipeline machine for both facades).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.distributed.fleet as fleet
from paddle_tpu.distributed.fleet.pp_layers import (LayerDesc,
                                                    PipelineLayer)


def _make_pipeline_layer(h=16, n_blocks=4, seed=3):
    paddle.seed(seed)

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(h, h)

        def forward(self, x):
            return paddle.tanh(self.fc(x)) + x

    descs = [LayerDesc(nn.Linear, 8, h)] \
        + [LayerDesc(Block) for _ in range(n_blocks)] \
        + [LayerDesc(nn.Linear, h, 4)]
    return PipelineLayer(descs, num_stages=2,
                         loss_fn=nn.CrossEntropyLoss())


def _init_fleet(mode, acc=4):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 2, "sharding_degree": 1}
    strategy.pipeline = True
    strategy.pipeline_configs = {"accumulate_steps": acc,
                                 "micro_batch_size": 2,
                                 "schedule_mode": mode}
    fleet.init(is_collective=True, strategy=strategy)
    return strategy


def _train(mode, steps=3):
    _init_fleet(mode)
    model = _make_pipeline_layer()
    model = fleet.distributed_model(model)
    from paddle_tpu.distributed.fleet.meta_parallel import \
        PipelineParallel
    assert isinstance(model, PipelineParallel)
    assert model.pp_schedule == {"1F1B": "1f1b", "ZBH1": "zbh1",
                                 "ZBV": "zbvpp"}[mode]
    opt = paddle.optimizer.SGD(0.05, parameters=model.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 8).astype("f4"))
    y = paddle.to_tensor(rng.randint(0, 4, (8,)))
    losses = [float(model.train_batch((x, y), opt))
              for _ in range(steps)]
    return losses, model


def test_train_batch_1f1b_decreases_and_matches_oracle():
    losses, model = _train("1F1B")
    assert losses[-1] < losses[0], losses
    # oracle: the SAME chain trained single-device (no pipeline)
    oracle = _make_pipeline_layer()        # same seed -> same init
    opt0 = paddle.optimizer.SGD(0.05,
                                parameters=oracle.parameters())
    ce = nn.CrossEntropyLoss()
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 8).astype("f4"))
    y = paddle.to_tensor(rng.randint(0, 4, (8,)))
    want = []
    for _ in range(3):
        loss = ce(oracle(x), y)
        loss.backward()
        opt0.step()
        opt0.clear_grad()
        want.append(float(loss))
    np.testing.assert_allclose(losses, want, rtol=1e-4)


def test_schedule_mode_zbh1_matches_1f1b():
    """schedule_mode=ZBH1 routes onto the compiled zero-bubble ring
    and computes the same losses as 1F1B (the schedules are
    numerically equivalent; only the timeline differs)."""
    l_zb, _ = _train("ZBH1")
    l_ref, _ = _train("1F1B")
    np.testing.assert_allclose(l_zb, l_ref, rtol=1e-4)


def test_schedule_mode_zbv_matches_1f1b():
    """ZBV/ZBVPP (two V-placed chunks; 4 blocks % 2*pp == 0)."""
    l_zbv, _ = _train("ZBV")
    l_ref, _ = _train("1F1B")
    np.testing.assert_allclose(l_zbv, l_ref, rtol=1e-4)


def test_schedule_mode_guards():
    """Unsupported schedule_mode is a TRAIN-path config error: the wrap
    succeeds (forward/eval-only flows keep working) and the error
    surfaces at train_batch()."""
    _init_fleet("FThenB")
    model = _make_pipeline_layer()
    wrapped = fleet.distributed_model(model)
    x = paddle.to_tensor(np.zeros((4, 8), "f4"))
    y = wrapped(x)                     # eval path works under FThenB
    assert y.shape == [4, 4]
    with pytest.raises(ValueError, match="schedule_mode"):
        wrapped.train_batch(
            (x, paddle.to_tensor(np.zeros((4,), "int64"))),
            optimizer=None)


def test_schedule_mode_error_lists_modes_and_raises_every_call():
    """ISSUE 15 satellite pinning the DEFERRED error path's contract:
    the wrap stays a fully working facade (forward AND state_dict),
    the error text names every supported mode so a config typo is
    self-diagnosing, and train_batch raises on EVERY call — a retry
    loop must not accidentally 'recover' from a config error."""
    _init_fleet("Eager1F1B")
    wrapped = fleet.distributed_model(_make_pipeline_layer())
    assert wrapped.pp_schedule is None
    x = paddle.to_tensor(np.zeros((4, 8), "f4"))
    assert wrapped(x).shape == [4, 4]
    assert wrapped.state_dict()
    y = paddle.to_tensor(np.zeros((4,), "int64"))
    for _ in range(2):
        with pytest.raises(ValueError) as ei:
            wrapped.train_batch((x, y), optimizer=None)
        for mode in ("1F1B", "ZBH1", "ZBVPP"):
            assert mode in str(ei.value), str(ei.value)


def test_train_batch_step_guard_detects_nonfinite_fused_step():
    """ISSUE 15: the fused pipeline step cannot skip an already-applied
    update, so the guard's fleet contract is detect + circuit-break:
    a poisoned batch ticks train.nan_steps and the breaker aborts."""
    import jax
    if not hasattr(jax.lax, "axis_size"):
        pytest.skip("jax API drift: lax.axis_size unavailable — the "
                    "compiled pipeline step fails at HEAD on this "
                    "container (same gate as the schedule-mode tests)")
    import paddle_tpu.observability as obs
    from paddle_tpu.training import NonFiniteStepError, StepGuard

    obs.enable()
    obs.REGISTRY.reset()
    _init_fleet("1F1B")
    model = fleet.distributed_model(_make_pipeline_layer())
    opt = paddle.optimizer.SGD(0.05, parameters=model.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 8).astype("f4"))
    y = paddle.to_tensor(rng.randint(0, 4, (8,)).astype("int64"))
    guard = StepGuard(max_consecutive_bad=1)
    model.train_batch((x, y), opt, step_guard=guard)   # finite: fine
    assert guard.nan_steps == 0
    bad = paddle.to_tensor(np.full((8, 8), np.inf, "f4"))
    with pytest.raises(NonFiniteStepError):
        model.train_batch((bad, y), opt, step_guard=guard)
    assert guard.nan_steps == 1
    assert obs.counter("train.nan_steps").value == 1


def test_heterogeneous_chain_passes_through_with_warning():
    """Structural incapability (no homogeneous block run) keeps the old
    pass-through behavior — forward works, a warning names the limit —
    while config errors (bad schedule_mode) still raise."""
    import warnings as _w
    _init_fleet("1F1B")
    paddle.seed(0)
    descs = [LayerDesc(nn.Linear, 8, 12), LayerDesc(nn.Linear, 12, 6),
             LayerDesc(nn.Linear, 6, 4)]
    het = PipelineLayer(descs, num_stages=2,
                        loss_fn=nn.CrossEntropyLoss())
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        out = fleet.distributed_model(het)
    assert any("PipelineParallel unavailable" in str(r.message)
               for r in rec), [str(r.message) for r in rec]
    y = out(paddle.randn([4, 8]))
    assert y.shape == [4, 4]


def test_dp2_pp2_hybrid_layout_matches_oracle():
    """dp2 x pp2 through the fleet facade: the batch shards over the
    compiled mesh's dp axis (no eager DataParallel wrapper) and the
    hcg-consistent pp-outer device layout trains to the same losses as
    the single-device oracle."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "pp_degree": 2, "sharding_degree": 1}
    strategy.pipeline = True
    strategy.pipeline_configs = {"accumulate_steps": 2,
                                 "schedule_mode": "1F1B"}
    fleet.init(is_collective=True, strategy=strategy)
    model = fleet.distributed_model(_make_pipeline_layer())
    opt = paddle.optimizer.SGD(0.05, parameters=model.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 8).astype("f4"))
    y = paddle.to_tensor(rng.randint(0, 4, (8,)))
    losses = [float(model.train_batch((x, y), opt)) for _ in range(3)]

    oracle = _make_pipeline_layer()
    opt0 = paddle.optimizer.SGD(0.05, parameters=oracle.parameters())
    ce = nn.CrossEntropyLoss()
    want = []
    for _ in range(3):
        loss = ce(oracle(x), y)
        loss.backward()
        opt0.step()
        opt0.clear_grad()
        want.append(float(loss))
    np.testing.assert_allclose(losses, want, rtol=1e-4)
