"""fleet.utils namespace (fs/LocalFS, timers, log_util,
hybrid_parallel_util, mix-precision main-grad wrappers) — reference
fleet/utils/* surface."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.fleet import utils as fleet_utils
from paddle_tpu.distributed.fleet.utils import (
    DistributedInfer, HDFSClient, LocalFS, recompute)


def test_namespace_exports():
    assert fleet_utils.__all__ == [
        "LocalFS", "recompute", "DistributedInfer", "HDFSClient"]
    # reference submodule paths resolve
    from paddle_tpu.distributed.fleet.utils import (  # noqa: F401
        fs, hybrid_parallel_util, log_util, mix_precision_utils,
        pp_parallel_adaptor, ps_util, sequence_parallel_utils,
        timer_helper)


def test_local_fs_roundtrip(tmp_path):
    fs = LocalFS()
    d = str(tmp_path / "ckpt")
    fs.mkdirs(d)
    assert fs.is_dir(d) and fs.is_exist(d)
    f = str(tmp_path / "ckpt" / "a.txt")
    fs.touch(f)
    assert fs.is_file(f)
    dirs, files = fs.ls_dir(str(tmp_path / "ckpt"))
    assert files == ["a.txt"] and dirs == []
    fs.mv(f, str(tmp_path / "ckpt" / "b.txt"))
    assert not fs.is_exist(f)
    fs.delete(d)
    assert not fs.is_exist(d)
    assert fs.need_upload_download() is False


def test_hdfs_client_raises_cleanly_without_hadoop():
    from paddle_tpu.distributed.fleet.utils.fs import ExecuteError
    c = HDFSClient(hadoop_home="/nonexistent")
    with pytest.raises(ExecuteError):
        c.mkdirs("/tmp/x")
    assert c.is_exist("/tmp/x") is False  # probe maps failure to False


def test_timers():
    from paddle_tpu.distributed.fleet.utils import timer_helper
    timers = timer_helper.set_timers()
    assert timer_helper.is_timer_initialized()
    t = timers("fwd")
    t.start()
    t.stop()
    assert t.elapsed(reset=False) >= 0.0
    msg = timers.log(["fwd"])
    assert "fwd" in msg


def test_log_util_levels():
    from paddle_tpu.distributed.fleet.utils import log_util
    log_util.set_log_level("DEBUG")
    assert log_util.get_log_level_name() == "DEBUG"
    log_util.set_log_level("INFO")
    assert log_util.layer_to_str("Linear", 4, 8, bias_attr=None) == \
        "Linear(4, 8, bias_attr=None)"


def test_fused_allreduce_gradients_single_rank():
    """With world=1 the allreduce is identity; grads survive and the
    scale divide is a no-op."""
    from paddle_tpu.distributed.fleet.utils.hybrid_parallel_util import (
        fused_allreduce_gradients_with_group,
        obtain_optimizer_parameters_list)
    m = nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=m.parameters())
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    m(x).sum().backward()
    params = obtain_optimizer_parameters_list(opt)
    assert len(params) == 2
    g0 = np.asarray(params[0].grad.numpy()).copy()
    fused_allreduce_gradients_with_group(params, group=None)
    np.testing.assert_allclose(np.asarray(params[0].grad.numpy()), g0)


def test_mix_precision_main_grad_accumulation():
    from paddle_tpu.distributed.fleet.utils.mix_precision_utils import (
        MixPrecisionLayer, MixPrecisionOptimizer)
    m = nn.Linear(3, 3)
    wrapped = MixPrecisionLayer(m)
    opt = MixPrecisionOptimizer(
        paddle.optimizer.SGD(learning_rate=0.5,
                             parameters=m.parameters()))
    x = paddle.to_tensor(np.ones((2, 3), np.float32))
    # two micro-batches accumulate into fp32 main_grad
    for _ in range(2):
        wrapped(x).sum().backward()
    w = m.weight
    assert w.main_grad is not None
    mg = np.asarray(w.main_grad.numpy())
    np.testing.assert_allclose(mg, np.full((3, 3), 4.0), atol=1e-6)
    before = np.asarray(w.numpy()).copy()
    opt.step()
    after = np.asarray(w.numpy())
    # stepped with the ACCUMULATED main grad (4.0), lr 0.5 -> -2.0
    np.testing.assert_allclose(after, before - 2.0, atol=1e-5)
    assert w.main_grad is None
    opt.clear_grad()


def test_distributed_infer_requires_ps():
    di = DistributedInfer().init_distributed_infer_env()
    with pytest.raises(RuntimeError):
        di.pull_sparse(0, np.array([1, 2]))


def test_fused_allreduce_no_implicit_divide():
    """Single-controller semantics: grads are already the global mean,
    so a multi-rank group must NOT shrink them (the reference's
    sum-then-divide discipline does not carry over)."""
    from paddle_tpu.distributed.fleet.utils.hybrid_parallel_util import (
        fused_allreduce_gradients_with_group)

    class FakeGroup:
        nranks = 4
        world_size = 4
    m = nn.Linear(4, 4)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    m(x).sum().backward()
    g0 = np.asarray(m.weight.grad.numpy()).copy()
    fused_allreduce_gradients_with_group(list(m.parameters()),
                                         group=FakeGroup())
    np.testing.assert_allclose(np.asarray(m.weight.grad.numpy()), g0)
    # explicit pre-scale is honored
    fused_allreduce_gradients_with_group(list(m.parameters()),
                                         group=FakeGroup(), scale=2.0)
    np.testing.assert_allclose(np.asarray(m.weight.grad.numpy()),
                               g0 / 2.0)
