"""Fused LM-head+CE (ops/fused_ce.py) vs naive softmax-CE oracle."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu.ops.fused_ce as fc


def _naive(x, w, tgt, mask):
    logits = (x.astype(jnp.float32) @ w.astype(jnp.float32).T)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, tgt[:, None], axis=-1)[:, 0]
    return jnp.sum(mask * (logz - picked)) / jnp.sum(mask)


@pytest.mark.parametrize("t,v,h,cap", [
    (64, 97, 32, 16),     # multi-chunk, divisible
    (64, 97, 32, 8192),   # single chunk
    (60, 33, 16, 16),     # non-divisible -> padded tail chunk
    (50, 33, 16, 50),     # chunk == t, odd size
])
def test_fused_lm_ce_matches_naive(t, v, h, cap, monkeypatch):
    monkeypatch.setattr(fc, "_CHUNK_CAP", cap)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(t, h), jnp.float32)
    w = jnp.asarray(rng.randn(v, h) * 0.1, jnp.float32)
    tgt = jnp.asarray(rng.randint(0, v, (t,)))
    mask = jnp.asarray((rng.rand(t) > 0.2).astype(np.float32))

    loss = fc.fused_lm_ce(x, w, tgt, mask)
    ref = _naive(x, w, tgt, mask)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)

    gx, gw, gm = jax.grad(fc.fused_lm_ce, argnums=(0, 1, 3))(
        x, w, tgt, mask)
    rx, rw, rm = jax.grad(_naive, argnums=(0, 1, 3))(x, w, tgt, mask)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                               atol=2e-5, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                               atol=2e-5, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(gm), np.asarray(rm),
                               atol=2e-5, rtol=1e-3)


def test_masked_positions_get_zero_grad(monkeypatch):
    monkeypatch.setattr(fc, "_CHUNK_CAP", 8)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(16, 8), jnp.float32)
    w = jnp.asarray(rng.randn(11, 8), jnp.float32)
    tgt = jnp.asarray(rng.randint(0, 11, (16,)))
    mask = jnp.asarray(([1.0] * 12) + ([0.0] * 4), jnp.float32)
    gx = jax.grad(fc.fused_lm_ce)(x, w, tgt, mask)
    assert float(jnp.abs(gx[12:]).max()) == 0.0
    assert float(jnp.abs(gx[:12]).max()) > 0.0


def test_all_masked_is_zero_not_nan():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(8, 4), jnp.float32)
    w = jnp.asarray(rng.randn(7, 4), jnp.float32)
    tgt = jnp.asarray(rng.randint(0, 7, (8,)))
    mask = jnp.zeros((8,), jnp.float32)
    loss, (gx, gw) = jax.value_and_grad(
        fc.fused_lm_ce, argnums=(0, 1))(x, w, tgt, mask)
    assert float(loss) == 0.0
    assert np.isfinite(np.asarray(gx)).all()
    assert float(jnp.abs(gx).max()) == 0.0 and \
        float(jnp.abs(gw).max()) == 0.0
