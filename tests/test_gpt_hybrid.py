"""Hybrid-parallel GPT engine on the virtual 8-device mesh: every
parallelism axis compiles and executes, and parallel losses match the
single-device run (the reference's hybrid_strategy loss-parity tests,
test/collective/fleet/hybrid_parallel_mp_model.py style)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models.gpt_hybrid import (ParallelConfig, build_mesh,
                                          init_params, setup, loss_fn,
                                          shard_params)


CFG = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4, num_heads=4,
                max_seq_len=16)


def _batch(b=8, s=16):
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, CFG.vocab_size, (b, s)))
    return ids, ids


def _ref_loss():
    pcfg = ParallelConfig(dp=1, pp=1, tp=1, param_dtype=jnp.float32,
                          compute_dtype=jnp.float32, remat=False)
    mesh = build_mesh(pcfg, jax.devices()[:1])
    params = init_params(CFG, pcfg, jax.random.PRNGKey(0))
    return float(loss_fn(params, _batch(), CFG, pcfg, mesh))


@pytest.mark.parametrize("pcfg_kw", [
    dict(dp=2, pp=1, tp=4),
    dict(dp=2, pp=1, tp=4, sp=True),
    dict(dp=1, pp=2, tp=2, microbatches=4),
    dict(dp=2, pp=2, tp=2, sp=True, microbatches=2),
])
def test_hybrid_loss_parity(pcfg_kw):
    ref = _ref_loss()
    pcfg = ParallelConfig(param_dtype=jnp.float32,
                          compute_dtype=jnp.float32, remat=False,
                          **pcfg_kw)
    mesh = build_mesh(pcfg)
    params = init_params(CFG, pcfg, jax.random.PRNGKey(0))
    with mesh:
        params, _ = shard_params(params, mesh, CFG, pcfg)
        loss = float(loss_fn(params, _batch(), CFG, pcfg, mesh))
    np.testing.assert_allclose(loss, ref, rtol=2e-5, atol=2e-5)


def test_train_step_runs_and_decreases():
    pcfg = ParallelConfig(dp=2, pp=2, tp=2, sp=True, microbatches=2,
                          param_dtype=jnp.float32,
                          compute_dtype=jnp.float32)
    mesh, params, opt_state, step = setup(CFG, pcfg, seed=0)
    batch = _batch()
    with mesh:
        losses = []
        for _ in range(5):
            params, opt_state, loss = step(params, opt_state, batch)
            losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_moe_expert_parallel():
    pcfg = ParallelConfig(dp=2, pp=1, tp=2, num_experts=4,
                          param_dtype=jnp.float32,
                          compute_dtype=jnp.float32)
    mesh, params, opt_state, step = setup(CFG, pcfg, seed=0,
                                          devices=jax.devices()[:4])
    batch = _batch()
    with mesh:
        losses = []
        for _ in range(5):
            params, opt_state, loss = step(params, opt_state, batch)
            losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
