"""Gradient merge (k-step accumulation) + no_sync deferral tests
(VERDICT r2 item 8; reference auto_parallel_gradient_merge.py and
DataParallel.no_sync)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def _mlp(seed=0):
    paddle.seed(seed)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def test_gradient_merge_matches_big_batch_sgd():
    """k merged microbatch steps == one step on the k-times batch."""
    paddle.seed(0)
    x = paddle.randn([16, 8])
    y = paddle.randn([16, 4])
    loss_fn = nn.MSELoss()

    m1 = _mlp()
    opt1 = paddle.optimizer.GradientMergeOptimizer(
        paddle.optimizer.SGD(0.1, parameters=m1.parameters()), k_steps=4)
    for i in range(4):
        loss = loss_fn(m1(x[i * 4:(i + 1) * 4]), y[i * 4:(i + 1) * 4])
        loss.backward()
        opt1.step()
        opt1.clear_grad()

    m2 = _mlp()
    opt2 = paddle.optimizer.SGD(0.1, parameters=m2.parameters())
    loss = loss_fn(m2(x), y)
    loss.backward()
    opt2.step()
    opt2.clear_grad()

    for p1, p2 in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy(), rtol=1e-6,
                                   atol=1e-6)


def test_gradient_merge_inner_not_stepped_midwindow():
    m = _mlp()
    inner = paddle.optimizer.SGD(0.1, parameters=m.parameters())
    opt = paddle.optimizer.GradientMergeOptimizer(inner, k_steps=3)
    w0 = m[0].weight.numpy().copy()
    x = paddle.randn([4, 8])
    for i in range(2):
        loss = paddle.mean(m(x))
        loss.backward()
        opt.step()
        opt.clear_grad()
        np.testing.assert_array_equal(m[0].weight.numpy(), w0)
    loss = paddle.mean(m(x))
    loss.backward()
    opt.step()
    assert not np.array_equal(m[0].weight.numpy(), w0)


def test_fleet_strategy_gradient_merge_wires_up():
    from paddle_tpu.distributed import fleet
    from paddle_tpu.optimizer.gradient_merge import GradientMergeOptimizer
    strategy = fleet.DistributedStrategy()
    strategy.gradient_merge = True
    strategy.gradient_merge_configs = {"k_steps": 2, "avg": True}
    fleet.init(is_collective=True, strategy=strategy)
    m = _mlp()
    opt = fleet.distributed_optimizer(
        paddle.optimizer.SGD(0.1, parameters=m.parameters()))
    assert isinstance(opt, GradientMergeOptimizer)
    assert opt._k_steps == 2


def test_hybrid_engine_compiled_gradient_merge():
    """ParallelConfig.gradient_merge_steps: merged compiled step matches
    the unmerged step on the same global batch."""
    import jax
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models.gpt_hybrid import ParallelConfig, setup
    cfg = GPTConfig.tiny()
    ids = np.random.default_rng(0).integers(0, 256, (8, 16))

    losses = {}
    params_out = {}
    for k in (1, 2):
        pcfg = ParallelConfig(dp=1, pp=1, tp=1, gradient_merge_steps=k,
                              remat=False)
        mesh, params, opt_state, step = setup(cfg, pcfg, seed=0,
                                              devices=jax.devices()[:1])
        batch = (ids, ids)
        with mesh:
            params, opt_state, loss = step(params, opt_state, batch)
        losses[k] = float(loss)
        params_out[k] = jax.tree_util.tree_map(np.asarray, params)
    assert np.isclose(losses[1], losses[2], rtol=1e-4)
    flat1 = jax.tree_util.tree_leaves(params_out[1])
    flat2 = jax.tree_util.tree_leaves(params_out[2])
    for a, b in zip(flat1, flat2):
        # chunked bf16 grad reduction can flip near-zero grad signs; the
        # first-Adam-step bound is 2*lr = 6e-4 for such params
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=7e-4)


def test_no_sync_defers_explicit_collectives():
    """Inside no_sync, framework collectives are recorded (no traffic);
    exit replays each deduped call once."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.distributed import collective as C
    from paddle_tpu.distributed.parallel import DataParallel
    from paddle_tpu.distributed.mesh import ProcessMesh

    mesh = ProcessMesh(shape=[len(jax.devices())], dim_names=["dp"])
    m = _mlp()
    dp = DataParallel(m, mesh=mesh)

    g = paddle.randn([8, 4])
    g._data = jax.device_put(g._data,
                             NamedSharding(mesh.jax_mesh, P("dp", None)))
    executed = []
    orig_put = jax.device_put

    def counting_put(*a, **k):
        executed.append(1)
        return orig_put(*a, **k)

    with dp.no_sync():
        jax.device_put = counting_put
        try:
            # the grad-sync collective fires twice (two microbatches)
            C.all_reduce(g)
            C.all_reduce(g)
            assert executed == []            # zero cross-device traffic
            assert not g._data.sharding.is_fully_replicated
        finally:
            jax.device_put = orig_put
    # on exit: replayed ONCE (deduped), grad now replicated
    assert g._data.sharding.is_fully_replicated


def test_no_sync_defers_stage2_relay():
    """GroupShardedStage2's grad re-lay hook is deferred under no_sync."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.distributed import collective as C
    from paddle_tpu.distributed.sharding import GroupShardedStage2
    from paddle_tpu.distributed.mesh import ProcessMesh

    mesh = ProcessMesh(shape=[len(jax.devices())], dim_names=["dp"])
    m = _mlp()
    st2 = GroupShardedStage2(m, group=None)
    x = paddle.randn([8, 8])
    with C.defer_collectives():
        loss = paddle.mean(st2(x))
        loss.backward()
        # inside the window no grad has been re-laid to the sharded spec
        for p in m.parameters():
            if p.grad is not None:
                assert p.grad._data.sharding.is_fully_replicated
    # after exit the largest-dim grads are group-sharded
    relaid = [p for p in m.parameters()
              if p.grad is not None
              and not p.grad._data.sharding.is_fully_replicated]
    assert relaid, "stage-2 re-lay should have fired at window exit"


from paddle_tpu.core.compat import HAS_MANUAL_AXES

_needs_manual_pp = pytest.mark.skipif(
    not HAS_MANUAL_AXES,
    reason="compiled-pipeline paths need jax's varying-manual-axes "
           "surface (lax.pcast / top-level shard_map); this jax "
           "predates it")


@_needs_manual_pp
def test_split_accum_composes_with_pipeline():
    """Gradient merge under pp in the compiled engines (VERDICT r3
    item 10): the split accum engine at pp=2 accumulates stage grads
    across k=2 outer 1F1B rounds; its update matches the FUSED
    gradient_merge_steps=2 run exactly (same chunks, same order) and a
    single 2x-microbatch step closely (same math, different reduction
    order). Reference: auto_parallel_gradient_merge.py composing with
    the pipeline passes."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                    num_heads=2, max_seq_len=32)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (8, 32)))
    base = dict(dp=1, pp=2, tp=1, microbatches=2, pp_schedule="1f1b",
                remat=True)

    def fresh_state(pcfg):
        mesh = GH.build_mesh(pcfg, jax.devices()[:2])
        with mesh:
            params = GH.init_params(cfg, pcfg, jax.random.PRNGKey(0))
            params, specs = GH.shard_params(params, mesh, cfg, pcfg)
            mspecs = GH.moment_specs(params, pcfg, specs)
            opt = GH.adamw_init(params, pcfg, mesh, specs, mspecs=mspecs)
        return mesh, params, opt, specs, mspecs

    # split engine: two half-batch 1F1B chunks + one apply
    pcfg = GH.ParallelConfig(**base)
    mesh, params, opt, specs, mspecs = fresh_state(pcfg)
    grad_step, apply_step = GH.build_accum_steps(
        cfg, pcfg, mesh, state_specs=(specs, mspecs))
    acc = GH.init_grad_accum(params)
    with mesh:
        acc, _ = grad_step(params, acc, (ids[:4], ids[:4]))
        acc, _ = grad_step(params, acc, (ids[4:], ids[4:]))
        p_split, _o, _a = apply_step(params, opt, acc, 2)

    # fused engine: gradient_merge_steps=2 over the same global batch
    pcfg_f = GH.ParallelConfig(gradient_merge_steps=2, **base)
    mesh_f, params_f, opt_f, _, _ = fresh_state(pcfg_f)
    step_f = GH.build_train_step(cfg, pcfg_f, mesh_f)
    with mesh_f:
        p_fused, _o, _l = step_f(params_f, opt_f, (ids, ids))

    for a, b in zip(jax.tree_util.tree_leaves(p_split),
                    jax.tree_util.tree_leaves(p_fused)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)

    # and a single 2x-microbatch step over the full batch (same update
    # math, reduction order differs -> close, not bitwise)
    pcfg_b = GH.ParallelConfig(**{**base, "microbatches": 4})
    mesh_b, params_b, opt_b, _, _ = fresh_state(pcfg_b)
    step_b = GH.build_train_step(cfg, pcfg_b, mesh_b)
    with mesh_b:
        p_big, _o, _l = step_b(params_b, opt_b, (ids, ids))
    for a, b in zip(jax.tree_util.tree_leaves(p_split),
                    jax.tree_util.tree_leaves(p_big)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@_needs_manual_pp
def test_gradient_merge_composes_with_zero_bubble_schedules():
    """gradient_merge_steps=2 at pp=2 produces the SAME update under
    the 1f1b, zbh1 and zbvpp compiled schedules — merge composes with
    the zero-bubble rings exactly as with 1F1B (the schedules compute
    identical gradients, so the merged update must be identical too)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                    num_heads=2, max_seq_len=32)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (8, 32)))
    outs = {}
    for sched in ("1f1b", "zbh1", "zbvpp"):
        pcfg = GH.ParallelConfig(dp=1, pp=2, tp=1, microbatches=2,
                                 pp_schedule=sched, remat=True,
                                 gradient_merge_steps=2)
        mesh, params, opt, step = GH.setup(cfg, pcfg, seed=0,
                                           devices=jax.devices()[:2])
        with mesh:
            p1, _o, loss = step(params, opt, (ids, ids))
        outs[sched] = (float(loss),
                       jax.tree_util.tree_leaves(
                           jax.tree_util.tree_map(np.asarray, p1)))
    for sched in ("zbh1", "zbvpp"):
        np.testing.assert_allclose(outs["1f1b"][0], outs[sched][0],
                                   rtol=2e-6)
        for a, b in zip(outs["1f1b"][1], outs[sched][1]):
            if a.shape != b.shape:     # zbvpp stacks blocks [pp,2,Lc]
                b = b.reshape(a.shape) if a.size == b.size else b
            assert a.size == b.size
            np.testing.assert_allclose(
                np.sort(a.reshape(-1)), np.sort(b.reshape(-1)),
                rtol=5e-5, atol=1e-6)
