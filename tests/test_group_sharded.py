"""Group-sharded (ZeRO 1/2/3) tests on the 8-device CPU mesh.

Mirrors the reference's loss-parity methodology
(test/collective/fleet/dygraph_group_sharded_stage3.py): each stage must
produce the same training trajectory as plain single-replica training,
while actually laying optimizer states / grads / params out sharded.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import (
    GroupShardedStage2, GroupShardedStage3, group_sharded_parallel,
    save_group_sharded_model)
from paddle_tpu.distributed.mesh import ProcessMesh, set_mesh


HID = 64  # divisible by 8 so every matrix shards


def _mesh():
    import jax
    mesh = ProcessMesh(shape=[len(jax.devices())], dim_names=["dp"])
    set_mesh(mesh)
    return mesh


def _model(seed=0):
    paddle.seed(seed)
    return nn.Sequential(nn.Linear(16, HID), nn.ReLU(),
                         nn.Linear(HID, 4))


def _train(model, opt, steps=4):
    lossfn = nn.CrossEntropyLoss()
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 16).astype("float32"))
    y = paddle.to_tensor(np.arange(8) % 4)
    losses = []
    for _ in range(steps):
        loss = lossfn(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


def _baseline():
    m = _model()
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=m.parameters())
    return _train(m, opt)


@pytest.mark.parametrize("level", ["os", "os_g", "p_g_os"])
def test_group_sharded_loss_parity(level):
    _mesh()
    expect = _baseline()
    m = _model()
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=m.parameters())
    m, opt, scaler = group_sharded_parallel(m, opt, level)
    got = _train(m, opt)
    np.testing.assert_allclose(got, expect, rtol=2e-5, atol=2e-6)


def test_stage1_sharded_optimizer_states():
    mesh = _mesh()
    m = _model()
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=m.parameters())
    m, opt, _ = group_sharded_parallel(m, opt, "os")
    _train(m, opt, steps=2)
    sharded = 0
    for _, d in opt._inner_opt._accumulators.items():
        for _, acc in d.items():
            sh = getattr(acc._data, "sharding", None)
            if sh is not None and not sh.is_fully_replicated:
                sharded += 1
    assert sharded > 0, "no optimizer accumulator ended up sharded"


def test_stage2_grads_sharded_after_backward():
    _mesh()
    m = GroupShardedStage2(_model())
    loss = m(paddle.to_tensor(
        np.random.RandomState(0).randn(8, 16).astype("float32"))).sum()
    loss.backward()
    sharded = 0
    for _, p in m.named_parameters():
        g = p.grad
        if g is None:
            continue
        sh = getattr(g._data, "sharding", None)
        if sh is not None and not sh.is_fully_replicated:
            sharded += 1
    assert sharded > 0, "no gradient ended up sharded"


def test_stage3_params_sharded_but_forward_exact():
    _mesh()
    ref = _model()
    x = paddle.to_tensor(np.random.RandomState(0).randn(8, 16)
                         .astype("float32"))
    expect = ref(x).numpy()
    m = GroupShardedStage3(_model())  # same seed -> same weights
    sharded = 0
    for _, p in m.named_parameters():
        sh = getattr(p._data, "sharding", None)
        if sh is not None and not sh.is_fully_replicated:
            sharded += 1
    assert sharded > 0, "no parameter ended up sharded"
    np.testing.assert_allclose(m(x).numpy(), expect, rtol=1e-5, atol=1e-6)


def test_save_group_sharded_model(tmp_path):
    _mesh()
    m = _model()
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=m.parameters())
    m, opt, _ = group_sharded_parallel(m, opt, "p_g_os")
    _train(m, opt, steps=1)
    out = str(tmp_path / "ckpt")
    save_group_sharded_model(m, out, optimizer=opt)
    state = paddle.load(out + "/model.pdmodel")
    fresh = _model(seed=123)
    fresh.set_state_dict(state)
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 16)
                         .astype("float32"))
    np.testing.assert_allclose(fresh(x).numpy(), m(x).numpy(),
                               rtol=1e-5, atol=1e-6)
