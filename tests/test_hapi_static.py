"""hapi Model.fit + metric + static Executor tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def test_hapi_fit_evaluate_predict(tmp_path):
    from paddle_tpu.hapi import Model
    from paddle_tpu.metric import Accuracy
    from paddle_tpu.vision.datasets import FakeData
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    net = LeNet()
    model = Model(net)
    model.prepare(paddle.optimizer.Adam(3e-3,
                                        parameters=net.parameters()),
                  nn.CrossEntropyLoss(), Accuracy())
    train = FakeData(size=32, image_shape=(1, 28, 28))
    hist = model.fit(train, epochs=4, batch_size=8, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0]
    logs = model.evaluate(train, batch_size=8, verbose=0)
    assert "loss" in logs and "acc" in logs
    preds = model.predict(train, batch_size=8)
    assert len(preds) == 4
    model.save(str(tmp_path / "ck"))
    model.load(str(tmp_path / "ck"))


def test_metrics():
    from paddle_tpu.metric import Accuracy, Precision, Recall, Auc
    acc = Accuracy()
    pred = paddle.to_tensor([[0.9, 0.1], [0.2, 0.8], [0.6, 0.4]])
    label = paddle.to_tensor([0, 1, 1])
    acc.update(acc.compute(pred, label))
    assert abs(acc.accumulate() - 2 / 3) < 1e-6

    p = Precision()
    p.update(np.array([0.9, 0.8, 0.2]), np.array([1, 0, 1]))
    assert abs(p.accumulate() - 0.5) < 1e-6

    r = Recall()
    r.update(np.array([0.9, 0.8, 0.2]), np.array([1, 0, 1]))
    assert abs(r.accumulate() - 0.5) < 1e-6

    auc = Auc()
    auc.update(np.array([0.9, 0.8, 0.3, 0.1]), np.array([1, 1, 0, 0]))
    assert auc.accumulate() > 0.9


def test_static_executor_roundtrip():
    import paddle_tpu.static as static
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [4, 3], "float32")
        outs = prog.record(lambda: {"y": (x * 2.0).sum(axis=1)})
    exe = static.Executor()
    feed_val = np.arange(12, dtype=np.float32).reshape(4, 3)
    (res,) = exe.run(prog, feed={"x": feed_val},
                     fetch_list=[outs["y"]])
    np.testing.assert_allclose(res, feed_val.sum(1) * 2)
    # second run with different values reuses the program
    feed2 = np.ones((4, 3), np.float32)
    (res2,) = exe.run(prog, feed={"x": feed2}, fetch_list=[outs["y"]])
    np.testing.assert_allclose(res2, np.full(4, 6.0))
