"""Tests for incubate (asp/autograd/optimizer), amp.debugging,
nn.quant, utils.dlpack, distributed.utils MoE comm ops (reference
analogs: test/asp, test/autograd, test/amp, test/quantization)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


class TestASP:
    def test_prune_gives_2_4_density(self):
        import paddle_tpu.incubate.asp as asp
        lin = nn.Linear(16, 16)
        asp.prune_model(lin)
        assert abs(asp.calculate_density(lin.weight) - 0.5) < 0.01
        # every group of 4 has exactly 2 nonzeros
        w = lin.weight.numpy().reshape(-1, 4)
        assert (np.count_nonzero(w, axis=1) <= 2).all()

    def test_decorated_optimizer_preserves_masks(self):
        import paddle_tpu.incubate.asp as asp
        lin = nn.Linear(8, 8)
        asp.prune_model(lin)
        zero_mask = lin.weight.numpy() == 0
        opt = asp.decorate(paddle.optimizer.SGD(
            learning_rate=0.5, parameters=lin.parameters()))
        x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
        for _ in range(3):
            loss = (lin(x) ** 2).mean()
            opt.clear_grad()
            loss.backward()
            opt.step()
        assert (lin.weight.numpy()[zero_mask] == 0).all()


class TestIncubateAutograd:
    def test_jvp_vjp(self):
        import paddle_tpu.incubate.autograd as iag
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        _, tang = iag.jvp(lambda t: t * t, [x])
        tg = tang[0] if isinstance(tang, list) else tang
        np.testing.assert_allclose(tg.numpy(), [2.0, 4.0])
        _, g = iag.vjp(lambda t: t * t, [x])
        gg = g[0] if isinstance(g, list) else g
        np.testing.assert_allclose(gg.numpy(), [2.0, 4.0])

    def test_jacobian_hessian(self):
        import paddle_tpu.incubate.autograd as iag
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        J = iag.Jacobian(lambda t: t * t, [x])
        assert J.shape == (2, 2)
        np.testing.assert_allclose(J[0].numpy(), [2.0, 0.0])
        H = iag.Hessian(lambda t: (t * t).sum(), [x])
        np.testing.assert_allclose(H[0].numpy(), [2.0, 0.0])


class TestLookAheadModelAverage:
    def test_lookahead_interpolates(self):
        lin = nn.Linear(4, 4)
        w0 = lin.weight.numpy().copy()
        la = paddle.incubate.LookAhead(paddle.optimizer.SGD(
            learning_rate=0.1, parameters=lin.parameters()),
            alpha=0.5, k=1)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        loss = (lin(x) ** 2).mean()
        la.clear_grad()
        loss.backward()
        la.step()
        # slow = w0 + 0.5*(fast - w0): strictly between w0 and fast
        assert not np.allclose(lin.weight.numpy(), w0)

    def test_model_average_apply_restore(self):
        lin = nn.Linear(4, 4)
        ma = paddle.incubate.ModelAverage(
            0.15, parameters=lin.parameters())
        w0 = lin.weight.numpy().copy()
        ma.step()
        lin.weight._assign_array(lin.weight._data * 3)
        ma.step()
        ma.apply()
        np.testing.assert_allclose(lin.weight.numpy(), 2 * w0,
                                   rtol=1e-5)
        ma.restore()
        np.testing.assert_allclose(lin.weight.numpy(), 3 * w0,
                                   rtol=1e-5)


class TestAmpDebugging:
    def test_operator_stats(self, capsys):
        import paddle_tpu.amp.debugging as dbg
        with dbg.collect_operator_stats():
            x = paddle.to_tensor(np.ones(4, np.float32))
            _ = x + x
        out = capsys.readouterr().out
        assert "op list" in out and "float32" in out

    def test_check_numerics_raises_on_nan(self):
        import paddle_tpu.amp.debugging as dbg
        with pytest.raises(RuntimeError):
            dbg.check_numerics(
                paddle.to_tensor(np.array([1.0, np.nan])), "op", "v")
        assert dbg.check_numerics(
            paddle.to_tensor(np.ones(3)), "op", "v") == (0, 0)


class TestNnQuant:
    def test_weight_quant_roundtrip(self):
        import paddle_tpu.nn.quant as q
        w = paddle.to_tensor(
            np.random.RandomState(0).randn(16, 8).astype(np.float32))
        qw, scale = q.weight_quantize(w)
        assert qw.numpy().dtype == np.int8
        deq = q.weight_dequantize(qw, scale, out_dtype="float32")
        assert np.abs(deq.numpy() - w.numpy()).max() < 0.05

    def test_weight_only_linear_matches_dense(self):
        import paddle_tpu.nn.quant as q
        rs = np.random.RandomState(1)
        w = paddle.to_tensor(rs.randn(8, 4).astype(np.float32))
        x = paddle.to_tensor(rs.randn(3, 8).astype(np.float32))
        qw, scale = q.weight_quantize(w)
        out = q.weight_only_linear(x, qw, weight_scale=scale)
        ref = x.numpy() @ (qw.numpy().astype(np.float32)
                           * scale.numpy())
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4,
                                   atol=1e-4)


class TestDlpack:
    def test_roundtrip_and_torch_interop(self):
        from paddle_tpu.utils.dlpack import from_dlpack, to_dlpack
        t = paddle.to_tensor(np.arange(6, dtype=np.float32))
        t2 = from_dlpack(to_dlpack(t))
        np.testing.assert_allclose(t2.numpy(), t.numpy())
        import torch
        tt = torch.arange(4, dtype=torch.float32)
        np.testing.assert_allclose(from_dlpack(tt).numpy(),
                                   [0, 1, 2, 3])


class TestMoeCommOps:
    def test_global_scatter_gather_roundtrip(self):
        from paddle_tpu.distributed.utils import (global_gather,
                                                  global_scatter)
        x = paddle.to_tensor(
            np.arange(12, dtype=np.float32).reshape(6, 2))
        counts = paddle.to_tensor(np.array([2, 1, 3]))
        s = global_scatter(x, counts, counts)
        back = global_gather(s, counts, counts)
        np.testing.assert_allclose(back.numpy(), x.numpy())
