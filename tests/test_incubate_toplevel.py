"""Top-level paddle.incubate surface (reference incubate/__init__.py
__all__) and the legacy graph operators / identity_loss / jit.inference."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import incubate

REF_ALL = ['LookAhead', 'ModelAverage', 'graph_khop_sampler',
           'graph_reindex', 'graph_sample_neighbors', 'graph_send_recv',
           'identity_loss', 'inference', 'segment_max', 'segment_mean',
           'segment_min', 'segment_sum', 'softmax_mask_fuse',
           'softmax_mask_fuse_upper_triangle']


def test_all_matches_reference():
    assert sorted(incubate.__all__) == sorted(REF_ALL)
    for name in REF_ALL:
        assert hasattr(incubate, name), name


def test_segment_alias():
    x = paddle.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.]],
                                  np.float32))
    ids = paddle.to_tensor(np.array([0, 0, 1]))
    out = incubate.segment_sum(x, ids)
    np.testing.assert_allclose(np.asarray(out.numpy()),
                               [[4., 6.], [5., 6.]])


def test_identity_loss_reductions():
    x = paddle.to_tensor(np.array([1., 2., 3.], np.float32))
    assert float(incubate.identity_loss(x, "sum").numpy()) == 6.0
    assert float(incubate.identity_loss(x, 1).numpy()) == 2.0
    np.testing.assert_allclose(
        np.asarray(incubate.identity_loss(x, "none").numpy()),
        [1., 2., 3.])
    with pytest.raises(ValueError):
        incubate.identity_loss(x, "bad")
    # grad flows (it is the loss head)
    x.stop_gradient = False
    incubate.identity_loss(x * 2, "sum").backward()
    np.testing.assert_allclose(np.asarray(x.grad.numpy()), [2., 2., 2.])


def test_graph_send_recv_legacy_name():
    x = paddle.to_tensor(np.array([[1.], [2.], [3.]], np.float32))
    src = paddle.to_tensor(np.array([0, 1, 2]))
    dst = paddle.to_tensor(np.array([1, 2, 1]))
    out = incubate.graph_send_recv(x, src, dst, pool_type="sum")
    np.testing.assert_allclose(np.asarray(out.numpy()),
                               [[0.], [4.], [2.]])


def test_graph_khop_sampler_two_hops():
    # chain graph 0->1->2->3 in CSC (colptr over dst, row = src ids)
    # edges: (0,1),(1,2),(2,3): row sorted by dst
    row = paddle.to_tensor(np.array([0, 1, 2]))
    colptr = paddle.to_tensor(np.array([0, 0, 1, 2, 3]))
    nodes = paddle.to_tensor(np.array([3]))
    src, dst, idx = incubate.graph_khop_sampler(row, colptr, nodes,
                                                [1, 1])
    idx_v = np.asarray(idx.numpy()).tolist()
    assert idx_v[0] == 3          # seed first
    assert set(idx_v) == {3, 2, 1}  # two hops up the chain
    assert len(np.asarray(src.numpy())) == 2


def test_jit_inference_decorator():
    from paddle_tpu import nn
    m = nn.Linear(4, 2)
    x = paddle.to_tensor(np.ones((3, 4), np.float32))
    ref = np.asarray(m(x).numpy())
    wrapped = incubate.inference(m)
    out = np.asarray(wrapped(x).numpy())
    np.testing.assert_allclose(out, ref, rtol=1e-5)

    @incubate.inference
    def f(t):
        return t * 2
    np.testing.assert_allclose(np.asarray(f(x).numpy()), 2 * np.ones((3, 4)))
