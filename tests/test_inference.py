"""Inference serving stack (paddle.inference — reference
AnalysisPredictor: config passes, zero-copy IO, engine caching)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.inference import Config, create_predictor

rng = np.random.RandomState(3)


def _model():
    paddle.seed(7)
    return nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))


def _cfg(model, **passes):
    cfg = Config()
    cfg.set_layer(model)
    return cfg


def test_basic_predict_and_handles():
    model = _model()
    cfg = _cfg(model)
    pred = create_predictor(cfg)
    x = rng.randn(5, 8).astype(np.float32)
    h = pred.get_input_handle("x")
    h.copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle("out").copy_to_cpu()
    ref = model(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_low_precision_pass():
    model = _model()
    cfg = _cfg(model)
    cfg.enable_low_precision_inference("bfloat16")
    pred = create_predictor(cfg)
    x = rng.randn(4, 8).astype(np.float32)
    out = pred.run([paddle.to_tensor(x)])[0]
    assert "bfloat16" in str(out.numpy().dtype) or \
        out.numpy().dtype == np.float32  # cast back on fetch is fine
    ref = _model()(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(np.asarray(out.numpy(), np.float32),
                               ref, rtol=0.05, atol=0.05)


def test_int8_weight_only_pass():
    model = _model()
    x = rng.randn(6, 8).astype(np.float32)
    ref = model(paddle.to_tensor(x)).numpy()
    cfg = _cfg(model)
    cfg.enable_int8_weight_only()
    pred = create_predictor(cfg)
    out = pred.run([paddle.to_tensor(x)])[0].numpy()
    # int8 weight-only: ~1% relative error budget
    np.testing.assert_allclose(out, ref, rtol=0.1, atol=0.05)
    # quantized payloads retained for introspection
    q_found = [p for _, p in model.named_parameters()
               if hasattr(p, "_int8_payload")]
    assert q_found and q_found[0]._int8_payload[0].dtype == np.int8


def test_shape_bucketing_bounds_executables():
    model = _model()
    cfg = _cfg(model)
    cfg.enable_shape_bucketing([4, 8, 16])
    pred = create_predictor(cfg)
    pred.warmup([[4, 8], [8, 8], [16, 8]])
    n0 = pred.get_execution_stats()["executables"]
    # every odd batch size maps onto the ladder: no new executables
    for b in (1, 3, 5, 7, 11, 13):
        x = rng.randn(b, 8).astype(np.float32)
        out = pred.run([paddle.to_tensor(x)])[0].numpy()
        assert out.shape == (b, 4)
        ref = model(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
    assert pred.get_execution_stats()["executables"] == n0
    assert pred.get_execution_stats()["bucket_pad_total"] >= 6


def test_async_predict():
    pred = create_predictor(_cfg(_model()))
    fut = pred.run_async([paddle.to_tensor(
        rng.randn(4, 8).astype(np.float32))])
    outs = fut.get()
    assert fut.done() and outs[0].shape == [4, 4]


def test_share_external_data_zero_copy():
    import jax.numpy as jnp
    pred = create_predictor(_cfg(_model()))
    dev = jnp.asarray(rng.randn(3, 8).astype(np.float32))
    h = pred.get_input_handle("x")
    h.share_external_data(dev)
    assert h._t._data is dev            # adopted, not copied
    out = pred.run()[0]
    assert out.shape == [3, 4]


def test_stats_and_warmup():
    pred = create_predictor(_cfg(_model()))
    pred.warmup([[2, 8]])
    s = pred.get_execution_stats()
    assert s["runs"] == 1 and s["warmup_shapes"] == [(2, 8)]
    assert s["last_latency_ms"] is not None
