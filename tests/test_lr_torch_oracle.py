"""LR scheduler curves vs torch equivalents (reference mechanism:
test/legacy_test/test_lr_scheduler.py numpy formulas)."""
import numpy as np
import torch

import paddle_tpu as paddle


def _torch_curve(sched_cls, steps, **kw):
    p = torch.nn.Parameter(torch.zeros(1))
    opt = torch.optim.SGD([p], lr=kw.pop("base_lr"))
    s = sched_cls(opt, **kw)
    out = []
    for _ in range(steps):
        out.append(opt.param_groups[0]["lr"])
        opt.step()
        s.step()
    return out


def _ours_curve(sched, steps):
    out = []
    for _ in range(steps):
        out.append(sched.get_lr())
        sched.step()
    return out


def test_step_decay_matches_torch():
    ours = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=3,
                                         gamma=0.5)
    ref = _torch_curve(torch.optim.lr_scheduler.StepLR, 10,
                       base_lr=0.1, step_size=3, gamma=0.5)
    np.testing.assert_allclose(_ours_curve(ours, 10), ref, rtol=1e-6)


def test_multistep_matches_torch():
    ours = paddle.optimizer.lr.MultiStepDecay(
        learning_rate=0.1, milestones=[2, 5], gamma=0.1)
    ref = _torch_curve(torch.optim.lr_scheduler.MultiStepLR, 8,
                       base_lr=0.1, milestones=[2, 5], gamma=0.1)
    np.testing.assert_allclose(_ours_curve(ours, 8), ref, rtol=1e-6)


def test_exponential_matches_torch():
    ours = paddle.optimizer.lr.ExponentialDecay(learning_rate=0.2,
                                                gamma=0.9)
    ref = _torch_curve(torch.optim.lr_scheduler.ExponentialLR, 8,
                       base_lr=0.2, gamma=0.9)
    np.testing.assert_allclose(_ours_curve(ours, 8), ref, rtol=1e-6)


def test_cosine_annealing_matches_torch():
    ours = paddle.optimizer.lr.CosineAnnealingDecay(
        learning_rate=0.1, T_max=10)
    ref = _torch_curve(torch.optim.lr_scheduler.CosineAnnealingLR, 10,
                       base_lr=0.1, T_max=10)
    np.testing.assert_allclose(_ours_curve(ours, 10), ref, rtol=1e-5)


def test_lambda_matches_torch():
    ours = paddle.optimizer.lr.LambdaDecay(
        learning_rate=0.5, lr_lambda=lambda e: 0.95 ** e)
    ref = _torch_curve(torch.optim.lr_scheduler.LambdaLR, 6,
                       base_lr=0.5, lr_lambda=lambda e: 0.95 ** e)
    np.testing.assert_allclose(_ours_curve(ours, 6), ref, rtol=1e-6)


def test_linear_warmup_shape():
    ours = paddle.optimizer.lr.LinearWarmup(
        learning_rate=0.1, warmup_steps=4, start_lr=0.0, end_lr=0.1)
    curve = _ours_curve(ours, 6)
    np.testing.assert_allclose(curve[:4],
                               [0.0, 0.025, 0.05, 0.075], rtol=1e-6)
    assert curve[4] == 0.1
