"""Lint-style gate: every metric the framework emits is declared in
the single canonical catalog (observability/catalog.py), and the
catalog carries no dead names — so dashboards, the Prometheus scrape
endpoint, and the ratio-based perf gate can never silently drift from
the emission sites (ISSUE 13 satellite).

Pure AST walk over paddle_tpu/ — no imports of the walked modules, no
jax; runs in well under a second."""
import ast
import pathlib

import paddle_tpu
from paddle_tpu.observability import catalog

#: method/function names whose first string-literal argument is a
#: metric name: the registry entry points plus known thin wrappers
#: (flash_attention's trace-time ``_count``; auto_tuner's ``_count``)
_EMITTERS = {"counter", "gauge", "histogram", "_count"}

PKG_ROOT = pathlib.Path(paddle_tpu.__file__).parent


def _emitted_names():
    """{metric name: [file:line, ...]} for every walker-visible
    emission site in the package."""
    out = {}
    for path in sorted(PKG_ROOT.rglob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name not in _EMITTERS:
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue  # dynamic name: the wrapper's own def site
            rel = path.relative_to(PKG_ROOT.parent)
            out.setdefault(node.args[0].value, []).append(
                f"{rel}:{node.lineno}")
    return out


def test_every_emitted_metric_is_cataloged():
    emitted = _emitted_names()
    assert emitted, "walker found no emission sites — it is broken"
    missing = {n: sites for n, sites in emitted.items()
               if n not in catalog.CATALOG}
    assert not missing, (
        "metric names emitted but missing from "
        "observability/catalog.py (add them there — single canonical "
        f"home): {missing}")


def test_catalog_has_no_dead_names():
    emitted = set(_emitted_names())
    dead = set(catalog.CATALOG) - emitted - catalog.internal_names()
    assert not dead, (
        "catalog entries with no emission site left in the code "
        f"(remove or mark internal=True): {sorted(dead)}")


def test_internal_names_really_registered():
    """internal=True entries bypass the walker, so pin their
    registration mechanics directly: the cardinality-overflow counter
    must exist under its cataloged name once a drop happens."""
    import paddle_tpu.observability as obs
    obs.enable()
    reg = obs.REGISTRY
    old_cap = reg.max_series_per_name
    reg.max_series_per_name = 2
    try:
        before = obs.counter("metrics.dropped_series").value
        for i in range(4):
            reg.counter("t.catalog_overflow", i=str(i)).inc()
        assert obs.counter("metrics.dropped_series").value == before + 2
    finally:
        reg.max_series_per_name = old_cap
    assert "metrics.dropped_series" in catalog.internal_names()


def test_serving_robustness_counters_cataloged():
    """The ISSUE 14 outcome counters are the perf-gate's
    'shed, never collapse' vocabulary: pin that each exists in the
    catalog with the right kind AND has a real emission site in the
    serving layer (not just a catalog entry someone forgot to wire)."""
    emitted = _emitted_names()
    expected = {
        "serving.rejected": "counter",
        "serving.timed_out": "counter",
        "serving.cancelled": "counter",
        "serving.step_retries": "counter",
        "serving.quarantined": "counter",
        "serving.degraded": "gauge",
    }
    for name, kind in expected.items():
        assert name in catalog.CATALOG, name
        assert catalog.CATALOG[name]["kind"] == kind, name
        sites = emitted.get(name, [])
        assert any("inference" in s for s in sites), (name, sites)


def test_training_robustness_counters_cataloged():
    """The ISSUE 15 train.* names are the training fault-drill
    vocabulary: pin that each exists in the catalog with the right
    kind AND has a real emission site in the layer that owns it."""
    emitted = _emitted_names()
    expected = {
        "train.nan_steps": ("counter", "paddle_tpu/training"),
        "train.skipped_steps": ("counter", "paddle_tpu/training"),
        "train.checkpoint_saves": ("counter", "paddle_tpu/training"),
        "train.hang_aborts": ("counter", "watchdog"),
        "train.straggler_ranks": ("gauge", "watchdog"),
        "train.restarts": ("counter", "elastic"),
        "train.preemptions": ("counter", "hapi"),
    }
    for name, (kind, where) in expected.items():
        assert name in catalog.CATALOG, name
        assert catalog.CATALOG[name]["kind"] == kind, name
        sites = emitted.get(name, [])
        assert any(where in s for s in sites), (name, sites)


def test_catalog_entries_well_formed():
    for name, d in catalog.CATALOG.items():
        assert d["kind"] in ("counter", "gauge", "histogram"), name
        assert d["help"], f"{name}: empty help string"
        assert isinstance(d["labels"], tuple), name
        # the check() helper gives a pointed error for unknown names
    try:
        catalog.check("no.such.metric")
    except KeyError as e:
        assert "catalog.py" in str(e)
    else:
        raise AssertionError("catalog.check accepted an unknown name")
