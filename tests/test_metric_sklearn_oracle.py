"""paddle.metric numerics vs sklearn oracles (reference mechanism:
test/legacy_test/test_metrics.py numpy checks)."""
import numpy as np
from sklearn import metrics as skm

import paddle_tpu as paddle
from paddle_tpu.metric import Accuracy, Auc, Precision, Recall

rs = np.random.RandomState(17)


def test_accuracy_top1():
    logits = rs.randn(32, 5).astype(np.float32)
    labels = rs.randint(0, 5, (32, 1)).astype(np.int64)
    m = Accuracy()
    corr = m.compute(paddle.to_tensor(logits), paddle.to_tensor(labels))
    m.update(corr)
    ref = skm.accuracy_score(labels.ravel(), logits.argmax(-1))
    np.testing.assert_allclose(m.accumulate(), ref, rtol=1e-6)


def test_precision_recall_binary():
    preds = rs.rand(64).astype(np.float32)
    labels = (rs.rand(64) > 0.5).astype(np.int64)
    p = Precision()
    p.update(preds, labels)
    r = Recall()
    r.update(preds, labels)
    hard = (preds > 0.5).astype(np.int64)
    np.testing.assert_allclose(
        p.accumulate(), skm.precision_score(labels, hard), rtol=1e-6)
    np.testing.assert_allclose(
        r.accumulate(), skm.recall_score(labels, hard), rtol=1e-6)


def test_auc_close_to_sklearn():
    # thresholded-bucket AUC (the reference's implementation) converges
    # to exact AUC with enough buckets
    scores = rs.rand(512).astype(np.float32)
    labels = (rs.rand(512) < scores).astype(np.int64)  # informative
    a = Auc(num_thresholds=4095)
    preds2 = np.stack([1 - scores, scores], 1)
    a.update(preds2, labels.reshape(-1, 1))
    ref = skm.roc_auc_score(labels, scores)
    np.testing.assert_allclose(a.accumulate(), ref, atol=2e-3)
