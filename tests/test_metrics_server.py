"""Pull-based Prometheus scrape endpoint (ISSUE 13): serving sessions
started under ``PADDLE_TPU_METRICS_PORT`` expose /metrics + /healthz;
the last session closing releases the port. The serving harness is
the same 4-wide fake LM test_observability uses — a few tiny compiles
total."""
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.observability as obs
from paddle_tpu import nn
from paddle_tpu.observability import server as obs_server


@pytest.fixture(autouse=True)
def _metrics_on():
    obs.enable()
    yield
    obs.enable()
    # never leak a shared server (or env) into later tests
    os.environ.pop(obs_server.PORT_ENV, None)
    while obs_server.shared_server() is not None:
        obs_server.session_finished()


class _TinyLM(nn.Layer):
    def __init__(self, vocab=17, hidden=4):
        super().__init__()
        self.emb = nn.Embedding(vocab, hidden)
        self.proj = nn.Linear(hidden, vocab)
        self._hidden = hidden

    def init_cache(self, batch_size, max_length=16):
        from paddle_tpu.inference.decode import init_static_cache
        return [init_static_cache(batch_size, max_length, 1,
                                  self._hidden)]

    def forward_with_cache(self, ids, caches):
        from paddle_tpu.inference.decode import cache_attention
        x = self.emb(ids)
        q = x.unsqueeze(2)
        out, c0 = cache_attention(q, q, q, caches[0])
        h = out.reshape([x.shape[0], x.shape[1], self._hidden])
        return self.proj(x + h), [c0]


def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


def _parse_prom(text):
    """{series_name_with_labels: float} from the exposition text."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        series, val = line.rsplit(" ", 1)
        out[series] = float(val)
    return out


def test_session_serves_metrics_and_releases_port():
    os.environ[obs_server.PORT_ENV] = "0"   # ephemeral: tests can't
    # pick a fixed port safely; the server reports what it bound
    from paddle_tpu.inference.decode import ContinuousBatchingSession
    paddle.seed(3)
    sess = ContinuousBatchingSession(_TinyLM(), max_slots=2,
                                     max_length=16)
    srv = obs_server.shared_server()
    assert srv is not None and sess._metrics_server is srv
    port = srv.port

    # generate some serving traffic so the scrape carries live values
    rng = np.random.RandomState(0)
    rids = [sess.submit(rng.randint(0, 17, (n,)), 3) for n in (3, 4)]
    out = sess.run()
    assert set(out) == set(rids)

    # healthz liveness probe
    status, ctype, body = _get(f"{srv.url}/healthz")
    assert status == 200 and json.loads(body) == {"status": "ok"}

    # scrape: exposition format, >= 3 known series parse with values
    status, ctype, body = _get(f"{srv.url}/metrics")
    assert status == 200 and ctype.startswith("text/plain")
    series = _parse_prom(body.decode("utf-8"))
    assert series["paddle_tpu_serving_requests_submitted"] >= 2
    assert series["paddle_tpu_serving_requests_completed"] >= 2
    assert series["paddle_tpu_serving_decode_tokens"] > 0
    assert "paddle_tpu_serving_request_latency_s_count" in series
    # the scrape itself is counted (second scrape sees the first)
    _, _, body2 = _get(f"{srv.url}/metrics")
    assert _parse_prom(body2.decode())["paddle_tpu_metrics_scrapes"] \
        >= 1

    # unknown route -> 404, not a crash
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(f"{srv.url}/nope")
    assert ei.value.code == 404

    # clean shutdown: close() releases the ref, server stops, the
    # port is free for a new bind
    sess.close()
    assert obs_server.shared_server() is None
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        _get(f"http://127.0.0.1:{port}/healthz", timeout=2)
    srv2 = obs.MetricsServer(port).start()   # rebind proves release
    srv2.stop()
    sess.close()                             # idempotent


def test_refcount_across_two_sessions():
    os.environ[obs_server.PORT_ENV] = "0"
    from paddle_tpu.inference.decode import DecodeSession
    paddle.seed(4)
    with DecodeSession(_TinyLM(), max_length=16) as a:
        srv = obs_server.shared_server()
        assert srv is not None
        with DecodeSession(_TinyLM(), max_length=16) as b:
            assert b._metrics_server is srv   # shared, not a 2nd port
        # first close: still serving for the outer session
        assert obs_server.shared_server() is srv
        status, _, _ = _get(f"{srv.url}/healthz")
        assert status == 200
    assert obs_server.shared_server() is None


def test_no_env_means_no_server():
    os.environ.pop(obs_server.PORT_ENV, None)
    from paddle_tpu.inference.decode import DecodeSession
    paddle.seed(5)
    with DecodeSession(_TinyLM(), max_length=16) as s:
        assert s._metrics_server is None
        assert obs_server.shared_server() is None


def test_bind_failure_degrades_not_raises(capsys):
    # occupy a port, then point the env at it: the session must still
    # construct and serve inference — telemetry never breaks serving
    blocker = obs.MetricsServer(0).start()
    os.environ[obs_server.PORT_ENV] = str(blocker.port)
    try:
        # ThreadingHTTPServer sets SO_REUSEADDR, so same-process
        # rebinding of a LISTENING port succeeds on some platforms;
        # force the error path deterministically with a bad value
        os.environ[obs_server.PORT_ENV] = "not-a-port"
        from paddle_tpu.inference.decode import DecodeSession
        paddle.seed(6)
        with DecodeSession(_TinyLM(), max_length=16) as s:
            assert s._metrics_server is None
        assert "metrics endpoint disabled" in capsys.readouterr().err
    finally:
        blocker.stop()
