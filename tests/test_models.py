"""Model zoo smoke + convergence tests (reference: test/book/ end-to-end
convergence + hybrid_strategy model scripts)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def _train_steps(model, batch_fn, steps=8, lr=1e-2):
    opt = paddle.optimizer.AdamW(lr, parameters=model.parameters())
    losses = []
    for i in range(steps):
        loss = model(*batch_fn(i))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


def test_gpt_tiny_trains():
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    m = GPTForCausalLM(GPTConfig.tiny())
    ids = paddle.randint(0, 256, [4, 32])
    losses = _train_steps(m, lambda i: (ids, ids), steps=8)
    assert losses[-1] < losses[0]


def test_llama_tiny_trains_and_generates():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    ids = paddle.randint(0, 256, [4, 32])
    losses = _train_steps(m, lambda i: (ids, ids), steps=8)
    assert losses[-1] < losses[0]
    out = m.generate(ids[:1, :8], max_new_tokens=4)
    assert out.shape == [1, 8 + 4 + 1] or out.shape[1] >= 12


def test_llama_gqa_kv_cache_matches_full_forward():
    from paddle_tpu.models.llama import LlamaConfig, LlamaModel
    paddle.seed(0)
    m = LlamaModel(LlamaConfig.tiny())
    m.eval()
    ids = paddle.randint(0, 256, [2, 12])
    full = m(ids)
    caches = m.init_cache(2)
    logits1, caches = m(ids[:, :8], 0, caches)
    logits2, caches = m(ids[:, 8:], 8, caches)
    np.testing.assert_allclose(full[:, :8].numpy(), logits1.numpy(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(full[:, 8:].numpy(), logits2.numpy(),
                               rtol=1e-4, atol=1e-4)


def test_bert_mlm_trains():
    from paddle_tpu.models.bert import BertConfig, BertForMaskedLM
    paddle.seed(0)
    m = BertForMaskedLM(BertConfig.tiny())
    ids = paddle.randint(0, 256, [4, 16])
    labels = ids.clone()
    losses = _train_steps(m, lambda i: (ids, None, None, labels), steps=8)
    assert losses[-1] < losses[0]


def test_bert_amp_o2():
    from paddle_tpu.models.bert import BertConfig, BertForMaskedLM
    paddle.seed(0)
    m = BertForMaskedLM(BertConfig.tiny())
    opt = paddle.optimizer.AdamW(1e-3, parameters=m.parameters())
    m, opt = paddle.amp.decorate(m, opt, level="O2", dtype="bfloat16")
    ids = paddle.randint(0, 256, [2, 16])
    with paddle.amp.auto_cast(level="O2", dtype="bfloat16"):
        loss = m(ids, labels=ids)
    loss.backward()
    opt.step()
    opt.clear_grad()
    assert np.isfinite(float(loss))
    # params stayed bf16; master weights fp32
    assert m.bert.pooler.weight.dtype == paddle.bfloat16


def test_moe_layer_trains():
    from paddle_tpu.models.moe import MoELayer
    paddle.seed(0)
    layer = MoELayer(32, 64, num_experts=4, top_k=2)
    head = nn.Linear(32, 8)
    params = layer.parameters() + head.parameters()
    opt = paddle.optimizer.Adam(1e-2, parameters=params)
    x = paddle.randn([4, 8, 32])
    y = paddle.randint(0, 8, [4, 8])
    ce = nn.CrossEntropyLoss()
    losses = []
    for _ in range(8):
        out = head(layer(x))
        loss = ce(out.reshape([-1, 8]), y.reshape([-1])) \
            + 0.01 * paddle.to_tensor(layer.aux_loss)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_resnet18_fakedata_trains():
    from paddle_tpu.vision.models import resnet18
    from paddle_tpu.vision.datasets import FakeData
    from paddle_tpu.io import DataLoader
    paddle.seed(0)
    model = resnet18(num_classes=10)
    opt = paddle.optimizer.Adam(1e-3, parameters=model.parameters())
    ce = nn.CrossEntropyLoss()
    loader = DataLoader(FakeData(size=16, image_shape=(3, 32, 32)),
                        batch_size=8)
    losses = []
    for epoch in range(4):
        for img, label in loader:
            loss = ce(model(img), label)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_vision_model_shapes():
    from paddle_tpu.vision.models import LeNet, mobilenet_v2, vgg11
    x = paddle.randn([1, 3, 64, 64])
    assert vgg11(num_classes=7)(
        paddle.randn([1, 3, 224, 224])).shape == [1, 7]
    assert mobilenet_v2(num_classes=5)(x).shape == [1, 5]
    assert LeNet()(paddle.randn([1, 1, 28, 28])).shape == [1, 10]


def test_transforms_pipeline():
    from paddle_tpu.vision import transforms as T
    t = T.Compose([T.Resize(40), T.RandomCrop(32),
                   T.RandomHorizontalFlip(), T.ToTensor(),
                   T.Normalize([0.5, 0.5, 0.5], [0.5, 0.5, 0.5])])
    img = (np.random.rand(48, 48, 3) * 255).astype(np.uint8)
    out = t(img)
    assert out.shape == [3, 32, 32]
    assert out.dtype == paddle.float32
