"""REAL multi-process collective test (reference mechanism: SURVEY §4.2
CommunicationTestDistBase — shell out to the launcher, run N worker
processes on localhost, assert per-rank numerical equality; gloo-on-CPU
is the reference's transport, the JAX coordination service + XLA:CPU
collectives are ours).

This exercises the paths that the single-process suite cannot: the
distributed/env.py jax.distributed bootstrap (PADDLE_MASTER →
coordinator), cross-process device visibility (2 processes × 1 CPU
device = a 2-device global mesh), a cross-process allgather, and the
multihost barrier."""
import os
import socket
import subprocess
import sys

WORKER = r'''
import os

from paddle_tpu._testing import force_cpu
force_cpu()
import jax
import numpy as np
import paddle_tpu.distributed as dist

group = dist.init_parallel_env()
rank = dist.get_rank()
world = dist.get_world_size()
assert world == 2, f"world={world}"
assert group.nranks == 2 and group.rank == rank
assert len(jax.devices()) == 2, jax.devices()      # global view
assert len(jax.local_devices()) == 1               # one per process

from jax.experimental import multihost_utils
got = multihost_utils.process_allgather(
    np.array([float(rank + 1)], np.float32))
np.testing.assert_allclose(np.asarray(got).ravel(), [1.0, 2.0])

# compiled SPMD collective across the two processes: shard a global
# [2, 4] batch over the process-spanning mesh and psum it
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map
mesh = Mesh(np.asarray(jax.devices()), ("dp",))
local = np.full((1, 4), float(rank + 1), np.float32)
garr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("dp", None)), local, (2, 4))

@jax.jit
def summed(x):
    return shard_map(lambda t: jax.lax.psum(t, "dp"), mesh=mesh,
                     in_specs=P("dp", None), out_specs=P())(x)

out = summed(garr)
# out is replicated: every process's addressable shard holds the sum
np.testing.assert_allclose(
    np.asarray(out.addressable_data(0)).ravel()[:4],
    [3.0] * 4)    # 1 + 2 summed over the dp axis

dist.barrier()
open(os.environ["MARKER_DIR"] + f"/ok.{rank}", "w").close()
print(f"rank {rank} OK", flush=True)
'''


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_bootstrap_and_allgather(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo"
    env["MARKER_DIR"] = str(tmp_path)
    env.pop("XLA_FLAGS", None)             # exactly 1 CPU device/proc
    port = _free_port()
    # start_new_session + killpg: on timeout the worker grandchildren
    # must die with the launcher (SIGKILLing only the launcher would
    # orphan workers blocked in jax.distributed.initialize)
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--master", f"127.0.0.1:{port}",
         str(script)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        start_new_session=True)
    try:
        _, stderr = proc.communicate(timeout=120)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, 9)
        proc.wait()
        raise
    assert proc.returncode == 0, stderr[-1200:]
    assert (tmp_path / "ok.0").exists() and (tmp_path / "ok.1").exists()
