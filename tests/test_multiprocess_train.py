"""Multi-process hybrid TRAINING step (the multi-host story, e2e).

Reference mechanism (SURVEY §4.2): multi-node is simulated by
multi-process on localhost; the reference runs its fleet hybrid loops
over NCCL/gloo across ranks. Here: 2 processes x 4 local CPU devices =
an 8-device global mesh whose dp axis SPANS the process boundary (the
DCN seam) while pp/tp stay process-local (the ICI seam) — exactly the
layout the hybrid engine prescribes for real multi-host TPU. The full
compiled dp2 x pp2 x tp2 train step (GSPMD collectives + the 1F1B
ppermute ring) runs across both processes, and the loss must match the
single-process 8-virtual-device oracle (rtol 1e-5 — cross-process
collective reduction order is not bitwise-stable; same seed, same
batch).
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np

WORKER = r'''
import os

from paddle_tpu._testing import force_cpu
force_cpu(4)                       # 4 local devices per process
import jax
import numpy as np
import jax.numpy as jnp
import paddle_tpu.distributed as dist

dist.init_parallel_env()
rank = dist.get_rank()
assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

from jax.sharding import NamedSharding, PartitionSpec as P
from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models import gpt_hybrid as GH

cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                num_heads=4, max_seq_len=16)
num_experts = int(os.environ.get("MP_TRAIN_EXPERTS", "0"))
pcfg = GH.ParallelConfig(dp=2, pp=2, tp=2, sp=num_experts == 0,
                         num_experts=num_experts, microbatches=2,
                         pp_schedule="1f1b", remat=True,
                         param_dtype=jnp.float32,
                         compute_dtype=jnp.float32)
mesh, params, opt_state, step = GH.setup(cfg, pcfg, seed=0,
                                         devices=jax.devices())

rng = np.random.RandomState(0)
ids = rng.randint(0, cfg.vocab_size, (8, 16))
# dp shards the batch over the process boundary: each process feeds
# its LOCAL half (the reference's per-rank data loader role)
gbatch = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("dp", None)),
    ids[rank * 4:(rank + 1) * 4].astype(np.int32), (8, 16))

with mesh:
    losses = []
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state,
                                       (gbatch, gbatch))
        losses.append(float(jax.device_get(
            loss.addressable_data(0))))

import json, pathlib
pathlib.Path(os.environ["MARKER_DIR"], f"loss.{rank}").write_text(
    json.dumps(losses))
print(f"rank {rank} losses {losses}", flush=True)
'''


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


import pytest


@pytest.mark.parametrize("experts", [0, 4])
def test_two_process_hybrid_train_matches_single_process(tmp_path,
                                                         experts):
    """experts=0: dense + Megatron-SP. experts=4: EP-over-dp MoE — the
    GShard all-to-all dispatch crosses the process boundary (the
    reference's multi-node global_scatter/gather over NCCL)."""
    # single-process oracle on the same 8 virtual devices
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                    num_heads=4, max_seq_len=16)
    pcfg = GH.ParallelConfig(dp=2, pp=2, tp=2, sp=experts == 0,
                             num_experts=experts, microbatches=2,
                             pp_schedule="1f1b", remat=True,
                             param_dtype=jnp.float32,
                             compute_dtype=jnp.float32)
    mesh, params, opt, step = GH.setup(cfg, pcfg, seed=0,
                                       devices=jax.devices()[:8])
    ids = np.random.RandomState(0).randint(0, 128, (8, 16))
    want = []
    with mesh:
        for _ in range(2):
            params, opt, loss = step(params, opt,
                                     (jnp.asarray(ids), jnp.asarray(ids)))
            want.append(float(loss))

    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo"
    env["MARKER_DIR"] = str(tmp_path)
    env["MP_TRAIN_EXPERTS"] = str(experts)
    # each worker provisions its own 4-device CPU backend (force_cpu)
    env.pop("XLA_FLAGS", None)
    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--master", f"127.0.0.1:{port}",
         str(script)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        start_new_session=True)
    try:
        _, stderr = proc.communicate(timeout=600)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, 9)
        proc.wait()
        raise
    assert proc.returncode == 0, stderr[-1500:]
    for r in (0, 1):
        got = json.loads((tmp_path / f"loss.{r}").read_text())
        np.testing.assert_allclose(got, want, rtol=1e-5, err_msg=(
            f"rank {r}: cross-process hybrid losses {got} != "
            f"single-process oracle {want}"))


WORKER_PP4 = r'''
import os

from paddle_tpu._testing import force_cpu
force_cpu(2)                       # 2 local devices per process
import jax
import numpy as np
import jax.numpy as jnp
import paddle_tpu.distributed as dist

dist.init_parallel_env()
rank = dist.get_rank()
assert len(jax.devices()) == 8 and len(jax.local_devices()) == 2

from jax.sharding import NamedSharding, PartitionSpec as P
from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models import gpt_hybrid as GH

cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=8,
                num_heads=4, max_seq_len=16)
# dp=1, pp=4, tp=2 over 4 processes x 2 devices: each pipeline STAGE
# is one process's tp pair, so every 1F1B ppermute hop crosses a
# process boundary — the DCN-crossing p2p case (reference: multi-node
# NCCL send/recv between pipeline ranks)
pcfg = GH.ParallelConfig(dp=1, pp=4, tp=2, sp=True, microbatches=4,
                         pp_schedule="1f1b", remat=True,
                         param_dtype=jnp.float32,
                         compute_dtype=jnp.float32)
mesh, params, opt_state, step = GH.setup(cfg, pcfg, seed=0,
                                         devices=jax.devices())

rng = np.random.RandomState(0)
ids = rng.randint(0, cfg.vocab_size, (4, 16)).astype(np.int32)
# dp=1: the batch is replicated; every process feeds the full array
gbatch = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P(None, None)), ids, (4, 16))

with mesh:
    losses = []
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state,
                                       (gbatch, gbatch))
        losses.append(float(jax.device_get(
            loss.addressable_data(0))))

import json, pathlib
pathlib.Path(os.environ["MARKER_DIR"], f"loss.{rank}").write_text(
    json.dumps(losses))
print(f"rank {rank} losses {losses}", flush=True)
'''


def test_four_process_pp_spanning_train_matches_single_process(
        tmp_path):
    """Round 5 (VERDICT r4 item 6): 4 processes x 2 devices with the
    PIPELINE axis spanning every process boundary — each 1F1B
    collective-permute hop is a cross-process (DCN-class) transfer,
    the case the 2-process test kept process-local. Loss must match
    the single-process 8-virtual-device oracle."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=8,
                    num_heads=4, max_seq_len=16)
    pcfg = GH.ParallelConfig(dp=1, pp=4, tp=2, sp=True, microbatches=4,
                             pp_schedule="1f1b", remat=True,
                             param_dtype=jnp.float32,
                             compute_dtype=jnp.float32)
    mesh, params, opt, step = GH.setup(cfg, pcfg, seed=0,
                                       devices=jax.devices()[:8])
    ids = np.random.RandomState(0).randint(0, 128, (4, 16))
    want = []
    with mesh:
        for _ in range(2):
            params, opt, loss = step(
                params, opt, (jnp.asarray(ids), jnp.asarray(ids)))
            want.append(float(loss))

    script = tmp_path / "worker_pp4.py"
    script.write_text(WORKER_PP4)
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo"
    env["MARKER_DIR"] = str(tmp_path)
    env.pop("XLA_FLAGS", None)
    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "4", "--master", f"127.0.0.1:{port}",
         str(script)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        start_new_session=True)
    try:
        _, stderr = proc.communicate(timeout=900)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, 9)
        proc.wait()
        raise
    assert proc.returncode == 0, stderr[-1500:]
    for r in range(4):
        got = json.loads((tmp_path / f"loss.{r}").read_text())
        np.testing.assert_allclose(got, want, rtol=1e-5, err_msg=(
            f"rank {r}: pp-spanning cross-process losses {got} != "
            f"single-process oracle {want}"))


WORKER_ZBTP = r'''
import os

# the manual-tp zero-bubble stage needs the sequential CPU thunk
# scheduler (see tests/conftest.py) — set BEFORE the backend exists
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_cpu_enable_concurrency_optimized_scheduler=false").strip()
from paddle_tpu._testing import force_cpu
force_cpu(4)                       # 4 local devices per process
import jax
import numpy as np
import jax.numpy as jnp
import paddle_tpu.distributed as dist

dist.init_parallel_env()
rank = dist.get_rank()
assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

from jax.sharding import NamedSharding, PartitionSpec as P
from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models import gpt_hybrid as GH

cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                num_heads=4, max_seq_len=16)
pcfg = GH.ParallelConfig(dp=2, pp=2, tp=2, sp=True, microbatches=4,
                         pp_schedule="zbh1", remat=True,
                         param_dtype=jnp.float32,
                         compute_dtype=jnp.float32, fused_ce=False)
# order devices so the PIPELINE axis spans the process boundary:
# (dp, pp, tp) with pp0 = this process, pp1 = the other — every
# zero-bubble ring hop AND drain-phase boundary crosses DCN while the
# manual tp collectives stay process-local
devs = jax.devices()
order = [devs[i] for i in (0, 1, 4, 5, 2, 3, 6, 7)]
mesh, params, opt_state, step = GH.setup(cfg, pcfg, seed=0,
                                         devices=order)

rng = np.random.RandomState(0)
ids = rng.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)
# with pp spanning the process boundary, each process's devices
# address pieces of BOTH dp shards — feed the full batch and let the
# util slice this process's addressable parts
gb = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("dp", None)), ids, (8, 16))

with mesh:
    losses = []
    for _ in range(2):
        params, opt_state, loss = step(params, opt_state, (gb, gb))
        losses.append(float(jax.device_get(
            loss.addressable_data(0))))

import json, pathlib
pathlib.Path(os.environ["MARKER_DIR"], f"loss.{rank}").write_text(
    json.dumps(losses))
print(f"rank {rank} zbh1-tp losses {losses}", flush=True)
'''


def test_two_process_zero_bubble_manual_tp_matches_single_process(
        tmp_path):
    """Round-5 frontier artifact: the compiled zero-bubble ZBH1 with
    the MANUAL-TP stage body runs ACROSS processes — cond-gated ring
    hops cross the process boundary while the in-branch tp collectives
    stay process-local. Loss must match the single-process oracle."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                    num_heads=4, max_seq_len=16)
    pcfg = GH.ParallelConfig(dp=2, pp=2, tp=2, sp=True, microbatches=4,
                             pp_schedule="zbh1", remat=True,
                             param_dtype=jnp.float32,
                             compute_dtype=jnp.float32, fused_ce=False)
    mesh, params, opt, step = GH.setup(cfg, pcfg, seed=0,
                                       devices=jax.devices()[:8])
    ids = np.random.RandomState(0).randint(0, 128, (8, 16))
    want = []
    with mesh:
        for _ in range(2):
            params, opt, loss = step(
                params, opt, (jnp.asarray(ids), jnp.asarray(ids)))
            want.append(float(loss))

    script = tmp_path / "worker_zbtp.py"
    script.write_text(WORKER_ZBTP)
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo"
    env["MARKER_DIR"] = str(tmp_path)
    env.pop("XLA_FLAGS", None)
    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--master", f"127.0.0.1:{port}",
         str(script)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        start_new_session=True)
    try:
        _, stderr = proc.communicate(timeout=900)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, 9)
        proc.wait()
        raise
    assert proc.returncode == 0, stderr[-1500:]
    for r in (0, 1):
        got = json.loads((tmp_path / f"loss.{r}").read_text())
        np.testing.assert_allclose(got, want, rtol=1e-5, err_msg=(
            f"rank {r}: cross-process zbh1-tp losses {got} != "
            f"single-process oracle {want}"))
