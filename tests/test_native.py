"""Native C++ runtime library tests (collation, fused image transform,
blocking queue)."""
import threading

import numpy as np
import pytest

from paddle_tpu import native


@pytest.fixture(scope="module")
def lib():
    if not native.available():
        pytest.skip("native toolchain unavailable")
    return native.get_lib()


def test_collate_matches_numpy(lib):
    rng = np.random.RandomState(0)
    samples = [rng.randn(3, 32, 32).astype(np.float32) for _ in range(16)]
    out = native.collate(samples)
    np.testing.assert_array_equal(out, np.stack(samples))


def test_fused_image_transform(lib):
    rng = np.random.RandomState(0)
    imgs = [rng.randint(0, 256, (8, 6, 3), dtype=np.uint8)
            for _ in range(5)]
    mean = np.array([0.48, 0.45, 0.4], np.float32)
    std = np.array([0.22, 0.22, 0.22], np.float32)
    out = native.u8hwc_to_f32chw_batch(imgs, mean, std)
    ref = (np.stack(imgs).astype(np.float32) / 255.0
           - mean.reshape(1, 1, 1, 3)) / std.reshape(1, 1, 1, 3)
    ref = ref.transpose(0, 3, 1, 2)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_blocking_queue_producer_consumer(lib):
    q = native.BlockingQueue(capacity=4)
    items = [bytes([i]) * (i + 1) for i in range(20)]
    got = []

    def producer():
        for it in items:
            assert q.push(it)
        q.close()

    t = threading.Thread(target=producer)
    t.start()
    while True:
        item = q.pop()
        if item is None:
            break
        got.append(item)
    t.join()
    assert got == items


def test_queue_blocks_when_full(lib):
    q = native.BlockingQueue(capacity=2)
    assert q.push(b"a") and q.push(b"b")
    state = {"pushed": False}

    def slow_push():
        q.push(b"c")
        state["pushed"] = True

    t = threading.Thread(target=slow_push)
    t.start()
    t.join(timeout=0.2)
    assert not state["pushed"]  # still blocked on full queue
    assert q.pop() == b"a"
    t.join(timeout=2)
    assert state["pushed"]
    q.close()


def test_dataloader_uses_native_collate():
    from paddle_tpu.io import DataLoader, Dataset

    class DS(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return np.full((64, 64), i, np.float32), np.int64(i)

    batches = list(DataLoader(DS(), batch_size=4))
    assert batches[0][0].shape == [4, 64, 64]
    assert float(batches[0][0][1, 0, 0]) == 1.0
