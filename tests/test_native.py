"""Native C++ runtime library tests (collation, fused image transform,
blocking queue)."""
import threading

import numpy as np
import pytest

from paddle_tpu import native


@pytest.fixture(scope="module")
def lib():
    if not native.available():
        pytest.skip("native toolchain unavailable")
    return native.get_lib()


def test_collate_matches_numpy(lib):
    rng = np.random.RandomState(0)
    samples = [rng.randn(3, 32, 32).astype(np.float32) for _ in range(16)]
    out = native.collate(samples)
    np.testing.assert_array_equal(out, np.stack(samples))


def test_fused_image_transform(lib):
    rng = np.random.RandomState(0)
    imgs = [rng.randint(0, 256, (8, 6, 3), dtype=np.uint8)
            for _ in range(5)]
    mean = np.array([0.48, 0.45, 0.4], np.float32)
    std = np.array([0.22, 0.22, 0.22], np.float32)
    out = native.u8hwc_to_f32chw_batch(imgs, mean, std)
    ref = (np.stack(imgs).astype(np.float32) / 255.0
           - mean.reshape(1, 1, 1, 3)) / std.reshape(1, 1, 1, 3)
    ref = ref.transpose(0, 3, 1, 2)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_blocking_queue_producer_consumer(lib):
    q = native.BlockingQueue(capacity=4)
    items = [bytes([i]) * (i + 1) for i in range(20)]
    got = []

    def producer():
        for it in items:
            assert q.push(it)
        q.close()

    t = threading.Thread(target=producer)
    t.start()
    while True:
        item = q.pop()
        if item is None:
            break
        got.append(item)
    t.join()
    assert got == items


def test_queue_blocks_when_full(lib):
    q = native.BlockingQueue(capacity=2)
    assert q.push(b"a") and q.push(b"b")
    state = {"pushed": False}

    def slow_push():
        q.push(b"c")
        state["pushed"] = True

    t = threading.Thread(target=slow_push)
    t.start()
    t.join(timeout=0.2)
    assert not state["pushed"]  # still blocked on full queue
    assert q.pop() == b"a"
    t.join(timeout=2)
    assert state["pushed"]
    q.close()


def test_dataloader_uses_native_collate():
    from paddle_tpu.io import DataLoader, Dataset

    class DS(Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return np.full((64, 64), i, np.float32), np.int64(i)

    batches = list(DataLoader(DS(), batch_size=4))
    assert batches[0][0].shape == [4, 64, 64]
    assert float(batches[0][0][1, 0, 0]) == 1.0


# ---------------------------------------------------------------------------
# TCPStore (native/src/store.cc; reference tcp_store.h:121 semantics)
# ---------------------------------------------------------------------------
def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_tcp_store_kv_counters_barrier(lib):
    port = _free_port()
    master = native.TCPStore("127.0.0.1", port, is_master=True,
                             world_size=3)
    results = {}

    def worker(rank):
        c = native.TCPStore("127.0.0.1", port, world_size=3)
        c.set(f"ep/{rank}", f"host{rank}")
        c.add("count", 1)
        c.barrier("b")
        results[rank] = c.list("ep/")
        c.close()

    ts = [threading.Thread(target=worker, args=(r,)) for r in (1, 2)]
    for t in ts:
        t.start()
    master.set("ep/0", "host0")
    master.add("count", 1)
    master.barrier("b")
    for t in ts:
        t.join()
    # after barrier every participant saw every endpoint
    assert master.list("ep/") == {f"ep/{r}": f"host{r}".encode()
                                  for r in range(3)}
    for r in (1, 2):
        assert len(results[r]) == 3
    assert int(master.get("count")) == 3
    assert master.check("ep/1") and not master.check("missing")
    master.delete_key("ep/1")
    assert not master.check("ep/1")
    master.close()


def test_tcp_store_blocking_get_and_timeout(lib):
    port = _free_port()
    master = native.TCPStore("127.0.0.1", port, is_master=True)
    got = {}

    def late_setter():
        import time
        time.sleep(0.15)
        c = native.TCPStore("127.0.0.1", port)
        c.set("late", b"\x01\x02")
        c.close()

    t = threading.Thread(target=late_setter)
    t.start()
    got["v"] = master.get("late", timeout=5)  # blocks until set
    t.join()
    assert got["v"] == b"\x01\x02"
    with pytest.raises(TimeoutError):
        master.get("never", timeout=0.1)
    master.close()


def test_elastic_tcp_kv_store(lib):
    from paddle_tpu.distributed.elastic import TCPKVStore
    port = _free_port()
    kv = TCPKVStore("127.0.0.1", port, is_master=True)
    kv.put("job/nodes/0", "a:1")
    kv.put("job/nodes/1", "b:2", ttl_s=600)
    kv.put("job/nodes/2", "c:3", ttl_s=-1)  # already expired
    got = kv.get_prefix("job/nodes/")
    assert got == {"job/nodes/0": "a:1", "job/nodes/1": "b:2"}
    kv.delete("job/nodes/0")
    assert "job/nodes/0" not in kv.get_prefix("job/nodes/")


# ---------------------------------------------------------------------------
# Host tracer + stats registry (native/src/tracer.cc)
# ---------------------------------------------------------------------------
def test_native_tracer_nested_spans(lib):
    native.tracer_clear()
    native.tracer_enable(True)
    native.tracer_begin("outer")
    native.tracer_begin("inner")
    native.tracer_end()
    native.tracer_end()
    native.tracer_enable(False)
    spans = {s[0]: s for s in native.tracer_spans()}
    assert set(spans) >= {"outer", "inner"}
    # inner nests within outer on the same thread
    assert spans["inner"][1] >= spans["outer"][1]
    assert spans["inner"][2] <= spans["outer"][2]
    assert spans["inner"][3] == spans["outer"][3]


def test_profiler_uses_native_tracer(lib):
    import paddle_tpu.profiler as profiler
    with profiler.Profiler() as p:
        with profiler.RecordEvent("step_span"):
            pass
    assert any(s.name == "step_span" for s in p._all_spans())


def test_stats_registry(lib):
    native.stat_update("test/alloc", 1000)
    native.stat_update("test/alloc", 500)
    native.stat_update("test/alloc", -1200)
    assert native.stat_current("test/alloc") == 300
    assert native.stat_peak("test/alloc") == 1500
    native.stat_reset_peak("test/alloc")
    assert native.stat_peak("test/alloc") == 300


def test_tcp_store_barrier_is_reusable(lib):
    port = _free_port()
    master = native.TCPStore("127.0.0.1", port, is_master=True,
                             world_size=2)
    order = []

    def worker():
        c = native.TCPStore("127.0.0.1", port, world_size=2)
        for i in range(3):
            c.barrier("loop")
            order.append(("w", i))
        c.close()

    t = threading.Thread(target=worker)
    t.start()
    for i in range(3):
        master.barrier("loop")
        order.append(("m", i))
    t.join()
    # every generation completed on both sides
    assert sorted(order) == [("m", 0), ("m", 1), ("m", 2),
                             ("w", 0), ("w", 1), ("w", 2)]
    master.close()


def test_tcp_store_hostname_resolution(lib):
    port = _free_port()
    master = native.TCPStore("localhost", port, is_master=True)
    master.set("k", b"v")
    assert master.get("k") == b"v"
    master.close()


def test_native_multislot_datafeed(tmp_path):
    """Native MultiSlot parser (native/src/datafeed.cc — reference
    data_feed.cc format: per slot '<count> v...' per line)."""
    import numpy as np
    from paddle_tpu import native

    p = tmp_path / "feed.txt"
    p.write_text("3 11 12 13 1 0.5\n1 7 1 0.25\n2 5 6 1 0.125\n")
    out = native.parse_multislot_file(str(p), [False, True])
    if out is None:
        pytest.skip("native toolchain unavailable")
    (ids, ioff), (vals, voff) = out
    assert ids.tolist() == [11, 12, 13, 7, 5, 6]
    assert ioff.tolist() == [0, 3, 4, 6]
    np.testing.assert_allclose(vals, [0.5, 0.25, 0.125])


def test_inmemory_dataset_slots(tmp_path):
    import numpy as np
    import paddle_tpu.distributed as dist

    p = tmp_path / "part-0"
    p.write_text("2 4 5 1 1.5\n1 9 1 2.5\n")
    ds = dist.InMemoryDataset()
    ds.set_filelist([str(p)])

    class V:
        def __init__(self, dtype):
            self.dtype = dtype
    ds.set_use_var([V("int64"), V("float32")])
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 2
    (ids, ioff), (vals, voff) = ds.slot_arrays()
    assert ids.tolist() == [4, 5, 9]
    batches = list(ds.batch_generator(batch_size=2))
    assert len(batches) == 1
    dense_ids, dense_vals = batches[0]
    assert dense_ids.numpy().tolist() == [[4, 5], [9, 0]]
    np.testing.assert_allclose(dense_vals.numpy().ravel(), [1.5, 2.5])
