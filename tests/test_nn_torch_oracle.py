"""nn.functional numerics vs torch oracles (reference mechanism:
OpTest with framework cross-checks; torch-CPU is the independent
implementation here). Covers the conv/pool/norm/interp family that the
numpy-oracle sweep can't express compactly."""
import numpy as np
import pytest
import torch
import torch.nn.functional as tF

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

rs = np.random.RandomState(3)


def t(x):
    return paddle.to_tensor(x)


def tt(x):
    return torch.tensor(x)


class TestConv:
    def test_conv2d_stride_pad_dilation(self):
        x = rs.randn(2, 3, 16, 16).astype(np.float32)
        w = rs.randn(8, 3, 3, 3).astype(np.float32)
        b = rs.randn(8).astype(np.float32)
        for stride, pad, dil in [(1, 1, 1), (2, 0, 1), (1, 2, 2)]:
            out = F.conv2d(t(x), t(w), t(b), stride=stride,
                           padding=pad, dilation=dil)
            ref = tF.conv2d(tt(x), tt(w), tt(b), stride=stride,
                            padding=pad, dilation=dil)
            np.testing.assert_allclose(out.numpy(), ref.numpy(),
                                       rtol=2e-4, atol=2e-4)

    def test_conv2d_groups(self):
        x = rs.randn(2, 4, 8, 8).astype(np.float32)
        w = rs.randn(8, 2, 3, 3).astype(np.float32)
        out = F.conv2d(t(x), t(w), groups=2, padding=1)
        ref = tF.conv2d(tt(x), tt(w), groups=2, padding=1)
        np.testing.assert_allclose(out.numpy(), ref.numpy(),
                                   rtol=2e-4, atol=2e-4)

    def test_conv2d_transpose(self):
        x = rs.randn(2, 4, 8, 8).astype(np.float32)
        w = rs.randn(4, 6, 3, 3).astype(np.float32)
        out = F.conv2d_transpose(t(x), t(w), stride=2, padding=1)
        ref = tF.conv_transpose2d(tt(x), tt(w), stride=2, padding=1)
        np.testing.assert_allclose(out.numpy(), ref.numpy(),
                                   rtol=2e-4, atol=2e-4)

    def test_conv1d_and_3d(self):
        x1 = rs.randn(2, 3, 20).astype(np.float32)
        w1 = rs.randn(5, 3, 3).astype(np.float32)
        np.testing.assert_allclose(
            F.conv1d(t(x1), t(w1), padding=1).numpy(),
            tF.conv1d(tt(x1), tt(w1), padding=1).numpy(),
            rtol=2e-4, atol=2e-4)
        x3 = rs.randn(1, 2, 6, 6, 6).astype(np.float32)
        w3 = rs.randn(4, 2, 3, 3, 3).astype(np.float32)
        np.testing.assert_allclose(
            F.conv3d(t(x3), t(w3), padding=1).numpy(),
            tF.conv3d(tt(x3), tt(w3), padding=1).numpy(),
            rtol=2e-4, atol=2e-4)


class TestPool:
    def test_max_avg_pool2d(self):
        x = rs.randn(2, 3, 12, 12).astype(np.float32)
        np.testing.assert_allclose(
            F.max_pool2d(t(x), kernel_size=3, stride=2).numpy(),
            tF.max_pool2d(tt(x), 3, 2).numpy(), rtol=1e-5)
        np.testing.assert_allclose(
            F.avg_pool2d(t(x), kernel_size=2, stride=2).numpy(),
            tF.avg_pool2d(tt(x), 2, 2).numpy(), rtol=1e-5)

    def test_adaptive_pools(self):
        x = rs.randn(2, 3, 13, 9).astype(np.float32)
        np.testing.assert_allclose(
            F.adaptive_avg_pool2d(t(x), 4).numpy(),
            tF.adaptive_avg_pool2d(tt(x), 4).numpy(), rtol=1e-5,
            atol=1e-6)
        np.testing.assert_allclose(
            F.adaptive_max_pool2d(t(x), 4).numpy(),
            tF.adaptive_max_pool2d(tt(x), 4).numpy(), rtol=1e-5)


class TestNorm:
    def test_batch_norm_train_eval(self):
        x = rs.randn(4, 3, 8, 8).astype(np.float32)
        g = rs.rand(3).astype(np.float32) + 0.5
        b = rs.randn(3).astype(np.float32)
        rm = np.zeros(3, np.float32)
        rv = np.ones(3, np.float32)
        out = F.batch_norm(t(x), t(rm.copy()), t(rv.copy()), t(g), t(b),
                           training=True)
        ref = tF.batch_norm(tt(x), tt(rm.copy()), tt(rv.copy()), tt(g),
                            tt(b), training=True)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=2e-4,
                                   atol=2e-4)

    def test_group_instance_norm(self):
        x = rs.randn(2, 4, 6, 6).astype(np.float32)
        np.testing.assert_allclose(
            F.group_norm(t(x), num_groups=2).numpy(),
            tF.group_norm(tt(x), 2).numpy(), rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(
            F.instance_norm(t(x)).numpy(),
            tF.instance_norm(tt(x)).numpy(), rtol=2e-4, atol=2e-4)


class TestInterpolate:
    @pytest.mark.parametrize("mode,align",
                             [("nearest", False), ("bilinear", False),
                              ("bilinear", True)])
    def test_interpolate_2d(self, mode, align):
        x = rs.randn(2, 3, 8, 8).astype(np.float32)
        kw = {} if mode == "nearest" else {"align_corners": align}
        out = F.interpolate(t(x), size=[12, 12], mode=mode, **kw)
        ref = tF.interpolate(tt(x), size=[12, 12], mode=mode,
                             **({} if mode == "nearest"
                                else {"align_corners": align}))
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=2e-4,
                                   atol=2e-4)


class TestLosses:
    def test_nll_kl_bce(self):
        logits = rs.randn(6, 5).astype(np.float32)
        labels = rs.randint(0, 5, 6).astype(np.int64)
        np.testing.assert_allclose(
            F.cross_entropy(t(logits), t(labels)).numpy(),
            tF.cross_entropy(tt(logits), tt(labels)).numpy(),
            rtol=1e-5, atol=1e-6)
        p = rs.rand(4, 3).astype(np.float32)
        q = rs.rand(4, 3).astype(np.float32)
        lp = np.log(p / p.sum(-1, keepdims=True))
        qn = q / q.sum(-1, keepdims=True)
        np.testing.assert_allclose(
            F.kl_div(t(lp), t(qn), reduction="batchmean").numpy(),
            tF.kl_div(tt(lp), tt(qn), reduction="batchmean").numpy(),
            rtol=1e-5, atol=1e-6)
        x = rs.rand(8).astype(np.float32)
        y = (rs.rand(8) > 0.5).astype(np.float32)
        np.testing.assert_allclose(
            F.binary_cross_entropy(t(x), t(y)).numpy(),
            tF.binary_cross_entropy(tt(x), tt(y)).numpy(),
            rtol=1e-5, atol=1e-6)
