"""paddle.nn.utils tests (reference: test/legacy_test/test_weight_norm*,
test_spectral_norm, test_clip_grad_*)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn.utils import (clip_grad_norm_, clip_grad_value_,
                                 parameters_to_vector,
                                 remove_weight_norm, spectral_norm,
                                 vector_to_parameters, weight_norm)


def _grads(lin, x):
    (lin(x) ** 2).mean().backward()


def test_clip_grad_norm_scales_to_max():
    lin = nn.Linear(4, 4)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(2, 4).astype(np.float32))
    _grads(lin, x)
    clip_grad_norm_(lin.parameters(), 0.1)
    total = np.sqrt(sum(float((p.grad.numpy() ** 2).sum())
                        for p in lin.parameters()
                        if p.grad is not None))
    assert total <= 0.11


def test_clip_grad_value_bounds_elements():
    lin = nn.Linear(4, 4)
    x = paddle.to_tensor(np.ones((2, 4), np.float32) * 3)
    _grads(lin, x)
    clip_grad_value_(lin.parameters(), 0.01)
    for p in lin.parameters():
        if p.grad is not None:
            assert np.abs(p.grad.numpy()).max() <= 0.01 + 1e-7


def test_param_vector_roundtrip():
    lin = nn.Linear(3, 5)
    vec = parameters_to_vector(lin.parameters())
    assert vec.numpy().size == 3 * 5 + 5
    vector_to_parameters(vec * 2, lin.parameters())
    vec2 = parameters_to_vector(lin.parameters())
    np.testing.assert_allclose(vec2.numpy(), vec.numpy() * 2, rtol=1e-6)


def test_weight_norm_preserves_forward():
    lin = nn.Linear(4, 4)
    x = paddle.to_tensor(np.random.RandomState(1)
                         .randn(2, 4).astype(np.float32))
    before = lin(x).numpy()
    weight_norm(lin)
    np.testing.assert_allclose(lin(x).numpy(), before, rtol=1e-4,
                               atol=1e-5)
    assert hasattr(lin, "weight_g") and hasattr(lin, "weight_v")
    remove_weight_norm(lin)
    np.testing.assert_allclose(lin(x).numpy(), before, rtol=1e-4,
                               atol=1e-5)


def test_spectral_norm_bounds_sigma():
    lin = nn.Linear(6, 6)
    lin.weight._assign_array(lin.weight._data * 10)
    spectral_norm(lin, n_power_iterations=5)
    x = paddle.to_tensor(np.random.RandomState(2)
                         .randn(2, 6).astype(np.float32))
    _ = lin(x)
    sigma = np.linalg.norm(lin.weight.numpy(), 2)
    assert sigma <= 1.2, sigma
