"""Unified telemetry layer (ISSUE 4): registry semantics, exporter
formats, disabled-path overhead, compile-cache tracking, and the
instrumented training / serving / loading paths.

Kept cheap per the tier-1 budget: the serving harness is a 4-wide fake
LM (3 tiny compiles total), the training run is a 2-step Linear fit.
"""
import importlib.util
import json
import os
import time
import types

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.observability as obs
from paddle_tpu import nn
from paddle_tpu.observability import metrics as met


@pytest.fixture(autouse=True)
def _metrics_on():
    """Every test here runs with metrics enabled and leaves them so
    (the session default); values are NOT reset — assertions use
    deltas or per-test metric names."""
    obs.enable()
    yield
    obs.enable()


# ---------------------------------------------------------------- registry
def test_counter_gauge_histogram_semantics():
    c = obs.counter("t.ctr")
    v0 = c.value
    c.inc()
    c.inc(2.5)
    assert c.value == pytest.approx(v0 + 3.5)

    g = obs.gauge("t.gauge")
    g.set(4)
    g.inc()
    g.dec(2)
    assert g.value == pytest.approx(3.0)

    h = obs.histogram("t.hist")
    for i in range(100):
        h.observe(i / 100)
    assert h.count == 100
    assert h.sum == pytest.approx(sum(i / 100 for i in range(100)))
    assert 0.4 <= h.percentile(0.5) <= 0.6
    snap = h._snapshot()
    assert snap["min"] == 0.0 and snap["max"] == 0.99
    assert snap["p99"] >= snap["p90"] >= snap["p50"]


def test_histogram_reservoir_bounded():
    h = obs.histogram("t.hist_bounded")
    for i in range(5000):
        h.observe(float(i))
    assert h.count == 5000
    assert len(h._reservoir) <= 512
    # reservoir stays a uniform sample: median near 2500
    assert 1500 <= h.percentile(0.5) <= 3500


def test_labels_are_distinct_series_and_types_conflict():
    a = obs.counter("t.lab", op="x")
    b = obs.counter("t.lab", op="y")
    assert a is not b
    a.inc(5)
    assert b.value == 0.0
    assert obs.counter("t.lab", op="x") is a  # cached identity
    with pytest.raises(TypeError):
        obs.gauge("t.lab", op="x")            # same series, other type


def test_registry_same_name_different_label_sets():
    obs.counter("t.multi").inc()
    obs.counter("t.multi", k="1").inc(2)
    vals = {tuple(sorted(d["labels"].items())): d["value"]
            for d in obs.dump() if d["name"] == "t.multi"}
    assert vals[()] == 1.0 and vals[(("k", "1"),)] == 2.0


# ---------------------------------------------------------------- exporters
def test_jsonl_export_parses():
    obs.counter("t.jsonl_probe").inc(7)
    lines = obs.to_jsonl().splitlines()
    parsed = [json.loads(ln) for ln in lines]
    assert len(parsed) == len(obs.dump())
    mine = [d for d in parsed if d["name"] == "t.jsonl_probe"]
    assert mine and mine[0]["value"] == 7.0 and mine[0]["type"] == "counter"
    assert "ts" in mine[0]


def test_prometheus_export_format():
    obs.counter("t.prom_ctr", stage="0").inc(3)
    h = obs.histogram("t.prom_hist")
    h.observe(1.0)
    h.observe(3.0)
    text = obs.to_prometheus()
    assert "# TYPE paddle_tpu_t_prom_ctr counter" in text
    assert 'paddle_tpu_t_prom_ctr{stage="0"} 3' in text
    assert "# TYPE paddle_tpu_t_prom_hist summary" in text
    assert "paddle_tpu_t_prom_hist_count 2" in text
    assert "paddle_tpu_t_prom_hist_sum 4" in text
    assert 'quantile="0.50"' in text


def test_dump_writes_files(tmp_path):
    obs.counter("t.dump_probe").inc()
    p_json = tmp_path / "m.json"
    p_prom = tmp_path / "m.prom"
    snap = obs.dump(str(p_json))
    obs.dump(str(p_prom), format="prom")
    doc = json.loads(p_json.read_text())
    assert any(d["name"] == "t.dump_probe" for d in doc["metrics"])
    assert any(d["name"] == "t.dump_probe" for d in snap)
    assert "paddle_tpu_t_dump_probe" in p_prom.read_text()


# ----------------------------------------- histogram edge cases (ISSUE 13)
def test_histogram_empty_and_single_observation():
    h = obs.histogram("t.hist_edge_empty")
    # empty reservoir: percentile -> None at every q, snapshot stays
    # the minimal {count, sum} form (no percentile keys to lie with)
    for q in (0.0, 0.5, 1.0):
        assert h.percentile(q) is None
    snap = h._snapshot()
    assert snap == {"count": 0, "sum": 0.0}
    # exporters agree at count=0: prometheus emits count/sum, no
    # quantile lines for this series
    text = obs.to_prometheus()
    assert "paddle_tpu_t_hist_edge_empty_count 0" in text
    assert 'paddle_tpu_t_hist_edge_empty{quantile' not in text

    # single observation: every percentile IS that observation, and
    # out-of-range q clamps instead of raising
    h.observe(3.5)
    for q in (-1.0, 0.0, 0.5, 0.99, 1.0, 2.0):
        assert h.percentile(q) == 3.5
    snap = h._snapshot()
    assert snap["count"] == 1 and snap["min"] == snap["max"] == 3.5
    assert snap["p50"] == snap["p90"] == snap["p99"] == 3.5
    assert snap["mean"] == 3.5


# ------------------------------------- label cardinality cap (ISSUE 13)
def test_label_cardinality_cap_drops_and_counts():
    reg = obs.REGISTRY
    old_cap = reg.max_series_per_name
    reg.max_series_per_name = 8
    try:
        dropped0 = obs.counter("metrics.dropped_series").value
        made = [obs.counter("t.cap_probe", rid=str(i)) for i in range(20)]
        for c in made:
            c.inc()
        # only the first 8 label-sets registered; the rest were
        # detached throwaways (call sites keep working) and counted
        series = [d for d in obs.dump() if d["name"] == "t.cap_probe"]
        assert len(series) == 8
        assert obs.counter("metrics.dropped_series").value == \
            dropped0 + 12
        # registered series are stable identities; overflow lookups
        # share ONE detached sink per (name, kind) — no per-call
        # allocation, still invisible to export
        assert obs.counter("t.cap_probe", rid="0") is made[0]
        over = obs.counter("t.cap_probe", rid="19")
        assert over is made[19]            # the shared sink
        assert over is not made[0]         # never a registered series
        over.inc(5)   # works, goes nowhere
        assert len([d for d in obs.dump()
                    if d["name"] == "t.cap_probe"]) == 8
        # the exempt overflow counter itself never drops
        assert any(d["name"] == "metrics.dropped_series"
                   for d in obs.dump())
    finally:
        reg.max_series_per_name = old_cap


# --------------------------------------- snapshot read API (ISSUE 13)
def test_snapshot_delta_window_and_rates():
    obs.counter("t.read_ctr", k="a").inc(10)
    obs.histogram("t.read_hist").observe(2.0)
    obs.gauge("t.read_gauge").set(1.0)
    before = obs.take_snapshot()
    assert before.value("t.read_ctr", k="a") == 10.0
    assert before.get("t.read_ctr", k="missing") is None
    assert "t.read_hist" in before

    obs.counter("t.read_ctr", k="a").inc(30)
    obs.histogram("t.read_hist").observe(4.0)
    obs.histogram("t.read_hist").observe(6.0)
    obs.gauge("t.read_gauge").set(7.5)
    after = obs.take_snapshot()

    d = obs.delta(before, after)
    assert d.value("t.read_ctr", k="a") == 30.0       # counter delta
    assert d.value("t.read_gauge") == 7.5             # gauge end-state
    h = d.hist("t.read_hist")                         # window stats
    assert h["count"] == 2 and h["sum"] == 10.0 and h["mean"] == 5.0
    # registry-only ratio: counter delta per histogram-sum second
    assert d.per("t.read_ctr", "t.read_hist",
                 labels={"k": "a"}) == pytest.approx(3.0)
    # series that moved in the window, and only those
    moved = {(c["name"], tuple(sorted(c["labels"].items())))
             for c in d.changed()}
    assert ("t.read_ctr", (("k", "a"),)) in moved
    assert ("t.read_gauge", ()) in moved

    with obs.window() as w:
        obs.counter("t.read_ctr", k="a").inc(5)
    assert w.value("t.read_ctr", k="a") == 5.0
    assert w.delta.dt >= 0.0

    # from_metrics round-trips a persisted snapshot (the BENCH
    # telemetry blob path the perf gate reads)
    blob = json.loads(json.dumps(after.metrics))
    restored = obs.Snapshot.from_metrics(blob)
    assert restored.value("t.read_ctr", k="a") == 40.0
    d2 = obs.delta(before, restored)
    assert d2.value("t.read_ctr", k="a") == 30.0


# ------------------------------------------------------------- off switch
def test_exporters_valid_when_disabled_mid_session():
    """PADDLE_TPU_METRICS=off / disable() mid-session: the read side
    must keep returning VALID (possibly frozen) output — a scrape or
    dump racing a disable() can never crash a serving process."""
    obs.counter("t.off_probe").inc(3)
    h = obs.histogram("t.off_hist")
    h.observe(1.0)
    obs.disable()
    try:
        snap = obs.dump()
        assert isinstance(snap, list) and snap
        assert any(d["name"] == "t.off_probe" and d["value"] == 3.0
                   for d in snap)
        for ln in obs.to_jsonl().splitlines():
            json.loads(ln)
        text = obs.to_prometheus()
        assert "paddle_tpu_t_off_probe 3" in text
        assert text.endswith("\n")
        # dump-to-file also stays valid
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            p = os.path.join(td, "off.json")
            obs.dump(p)
            assert json.load(open(p))["metrics"]
        # writes are inert while off; the frozen values persist
        obs.counter("t.off_probe").inc(100)
        h.observe(9.0)
        assert obs.counter("t.off_probe").value == 3.0
        assert h.count == 1
    finally:
        obs.enable()


def test_disabled_is_noop_and_near_zero_cost():
    c = obs.counter("t.disabled_probe")
    h = obs.histogram("t.disabled_hist")
    obs.disable()
    try:
        c.inc(100)
        h.observe(1.0)
        g = obs.gauge("t.disabled_gauge")
        g.set(5)
        assert c.value == 0.0 and h.count == 0 and g.value == 0.0
        # micro-benchmark: the disabled mutate path is one branch —
        # generous absolute bound that still catches an accidental
        # lock/time/dict on the disabled path
        n = 50000
        t0 = time.perf_counter()
        for _ in range(n):
            c.inc()
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 2.5e-6, f"disabled inc() costs {per_call:.2e}s"
        # the framework's hot-path guard pattern (module-global bool)
        t0 = time.perf_counter()
        for _ in range(n):
            if met._ENABLED:
                c.inc()
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 1.0e-6, f"guard branch costs {per_call:.2e}s"
    finally:
        obs.enable()
    c.inc()
    assert c.value == 1.0


def test_env_flag_off_disables_at_import():
    spec = importlib.util.spec_from_file_location("_met_env_probe",
                                                  met.__file__)
    old = os.environ.get("PADDLE_TPU_METRICS")
    os.environ["PADDLE_TPU_METRICS"] = "off"
    try:
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod._ENABLED is False
    finally:
        if old is None:
            del os.environ["PADDLE_TPU_METRICS"]
        else:
            os.environ["PADDLE_TPU_METRICS"] = old


# ------------------------------------------------------ compile tracking
def test_compile_counter_on_toy_jit_fn():
    import jax
    import jax.numpy as jnp

    def f(x):
        return x * 2 + 1

    jf = jax.jit(f)
    with obs.count_compiles() as compiles, obs.count_traces() as traces:
        jf(jnp.ones((3,)))
    assert compiles() >= 1 and traces() >= 1
    # steady state: cache hit, zero events
    with obs.count_compiles() as c2, obs.count_traces() as t2:
        jf(jnp.ones((3,)))
    assert c2() == 0 and t2() == 0
    # liveness: a new shape must be SEEN
    with obs.count_compiles() as c3:
        jf(jnp.ones((4,)))
    assert c3() >= 1


def test_global_compile_counter_and_static_function_stats():
    before = obs.counter("jit.xla_compiles").value

    @paddle.jit.to_static
    def g(a):
        return a * 3

    x = paddle.to_tensor(np.ones((2,), "f4"))
    g(x)
    g(x)
    assert obs.counter("jit.xla_compiles").value > before
    assert g._m_calls.value >= 2
    assert g._m_builds.value >= 1
    assert g._m_hits.value >= 1
    rep = obs.compile_report()
    mine = [r for r in rep if r["function"].endswith("g")]
    assert mine and mine[0]["xla_executables"] >= 1
    # registry snapshot carries the aggregate gauges via the collector
    snap = {d["name"]: d for d in obs.dump() if not d["labels"]}
    assert snap["jit.static_functions"]["value"] >= 1
    assert snap["jit.xla_executables"]["value"] >= 1


# ------------------------------------------------- pad_mask_arg satellite
def test_pad_mask_arg_unbound_dynamic_dim_raises_clear_error():
    from paddle_tpu.jit import InputSpec

    def step(x, seq_mask):
        return (x * seq_mask).sum()

    st = paddle.jit.to_static(
        step,
        input_spec=[InputSpec([4], "float32"),
                    InputSpec([None], "float32")],
        pad_dynamic_dims=True, pad_mask_arg="seq_mask")
    with pytest.raises(ValueError, match="length is unknown"):
        st(paddle.to_tensor(np.ones((4,), "f4")))


# ------------------------------------------- fleet facade satellite
def test_meta_parallel_defers_schedule_error_to_train_batch():
    from paddle_tpu.distributed.fleet.meta_parallel import (
        PipelineParallel)

    class _Topo:
        def get_hybrid_group_names(self):
            return []

        def get_dim(self, name):
            return 1

    class _Hcg:
        def get_pipe_parallel_world_size(self):
            return 2

        def get_data_parallel_world_size(self):
            return 1

        def get_model_parallel_world_size(self):
            return 1

        def topology(self):
            return _Topo()

    lin = nn.Linear(3, 3)
    strategy = types.SimpleNamespace(
        pipeline_configs={"schedule_mode": "FThenB"})
    pp = PipelineParallel(lin, _Hcg(), strategy)
    # forward/eval-only flow keeps working after the wrap
    x = paddle.to_tensor(np.ones((2, 3), "f4"))
    y = pp(x)
    assert tuple(y.shape) == (2, 3)
    with pytest.raises(ValueError, match="schedule_mode"):
        pp.train_batch((x, x), optimizer=None)


# --------------------------------------------------- training run metrics
def test_training_run_produces_step_metrics():
    x = paddle.to_tensor(np.random.RandomState(0)
                         .rand(8, 4).astype("f4"))
    y = paddle.to_tensor(np.random.RandomState(1)
                         .rand(8, 1).astype("f4"))
    from paddle_tpu.io import TensorDataset
    net = nn.Linear(4, 1)
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=net.parameters()),
                  nn.MSELoss())
    steps0 = obs.counter("train.steps").value
    fetch0 = obs.histogram("dataloader.fetch_wait_s").count
    model.fit(TensorDataset([x, y]), batch_size=4, epochs=1, verbose=0)
    assert obs.counter("train.steps").value >= steps0 + 2
    assert obs.histogram("train.step_time_s").count >= 2
    assert obs.gauge("train.samples_per_s").value > 0
    assert obs.histogram("dataloader.fetch_wait_s").count >= fetch0 + 2


def test_mfu_gauge_from_configured_flops():
    obs.training.configure(flops_per_token=6e9, peak_flops=1e12)
    try:
        obs.training.record_step(0.01, samples=2, tokens=64)
        mfu = obs.gauge("train.mfu").value
        assert mfu == pytest.approx((64 / 0.01) * 6e9 / 1e12)
    finally:
        obs.training.configure(flops_per_token=0,
                               peak_flops=obs.training.DEFAULT_PEAK_FLOPS)
        obs.training._flops_per_token = None


def test_pipeline_bubble_gauge_math():
    from paddle_tpu.parallel.pipeline_1f1b import (
        _record_schedule_metrics, compiled_1f1b_schedule)
    _record_schedule_metrics("t1f1b", compiled_1f1b_schedule, 4, 8)
    bub = obs.gauge("pipeline.bubble_fraction", schedule="t1f1b").value
    mk, want = compiled_1f1b_schedule(4, 8).simulate()
    assert bub == pytest.approx(want)
    assert 0.0 < bub < 1.0
    assert obs.gauge("pipeline.makespan_ticks",
                     schedule="t1f1b").value == pytest.approx(mk)


# --------------------------------------------------- serving run metrics
class _TinyLM(nn.Layer):
    """Minimal cached causal LM for the cb-session harness — one
    embedding + cache attention + head; a few tiny compiles total."""

    def __init__(self, vocab=17, hidden=4):
        super().__init__()
        self.emb = nn.Embedding(vocab, hidden)
        self.proj = nn.Linear(hidden, vocab)
        self._hidden = hidden

    def init_cache(self, batch_size, max_length=16):
        from paddle_tpu.inference.decode import init_static_cache
        return [init_static_cache(batch_size, max_length, 1,
                                  self._hidden)]

    def forward_with_cache(self, ids, caches):
        from paddle_tpu.inference.decode import cache_attention
        x = self.emb(ids)                      # [B, S, H]
        q = x.unsqueeze(2)                     # [B, S, 1, H]
        out, c0 = cache_attention(q, q, q, caches[0])
        h = out.reshape([x.shape[0], x.shape[1], self._hidden])
        return self.proj(x + h), [c0]


def test_cb_session_metrics_and_rid_release():
    from paddle_tpu.inference.decode import ContinuousBatchingSession
    paddle.seed(11)
    m = _TinyLM()
    sess = ContinuousBatchingSession(m, max_slots=2, max_length=16)
    lat0 = obs.histogram("serving.request_latency_s").count
    tok0 = obs.counter("serving.decode_tokens").value
    rng = np.random.RandomState(2)
    rids = [sess.submit(rng.randint(0, 17, (n,)), 4)
            for n in (3, 5, 2)]
    assert obs.gauge("serving.inflight_requests").value == 3
    out = sess.run()
    assert set(out) == set(rids)
    for rid in rids:
        assert out[rid].shape[0] >= 4

    # satellite: delivered rids leave _used_rids -> no leak, id reuse ok
    assert sess._used_rids == set()
    assert obs.gauge("serving.inflight_requests").value == 0
    rid_again = sess.submit(rng.randint(0, 17, (3,)), 2,
                            request_id=rids[0])
    assert rid_again == rids[0]
    out2 = sess.run()
    assert set(out2) == {rids[0]}

    # instrumentation: latency histogram and token counters moved,
    # queue-depth / utilization gauges exist in the snapshot
    assert obs.histogram("serving.request_latency_s").count >= lat0 + 3
    assert obs.counter("serving.decode_tokens").value > tok0
    snap = {d["name"] for d in obs.dump()}
    for name in ("serving.queue_depth", "serving.slot_utilization",
                 "serving.decode_tokens_per_s",
                 "serving.prefill_tokens"):
        assert name in snap, f"missing {name}"


def test_chrome_trace_carries_metric_counter_events(tmp_path):
    obs.counter("t.trace_probe").inc(9)
    import paddle_tpu.profiler as prof
    p = prof.Profiler()
    p.start()
    _ = paddle.to_tensor(np.ones((2, 2), "f4")) * 2
    p.stop()
    path = str(tmp_path / "trace.json")
    p._export_chrome(path)
    events = json.load(open(path))["traceEvents"]
    counters = [e for e in events if e.get("ph") == "C"]
    assert counters, "no counter events in chrome trace"
    names = {e["name"] for e in counters}
    assert "metric::t.trace_probe" in names
    probe = [e for e in counters
             if e["name"] == "metric::t.trace_probe"][0]
    assert probe["args"]["value"] == 9.0
