"""Op-schema coverage manifest (VERDICT r1 item 5): every schema in
ops/yaml/ops.yaml must be exercised by at least one numeric-oracle
test, or carry an explicit audited pointer/exemption — the repo's
analog of the reference's test/white_list/ bookkeeping.

The sweep tables (test_ops_sweep*.py CASES) are discovered
automatically; everything else is accounted for in the audited maps
below. This test FAILS when a new schema is added without coverage,
or when a manifest entry goes stale (claims sweep coverage that no
longer exists).
"""
import re
from pathlib import Path

TESTS = Path(__file__).parent
YAML = TESTS.parent / "paddle_tpu" / "ops" / "yaml" / "ops.yaml"

SWEEP_FILES = ["test_ops_sweep.py", "test_ops_sweep2.py",
               "test_ops_sweep3.py", "test_ops_sweep4.py",
               "test_ops_sweep5.py"]

#: schemas exercised by named function-style tests (not table rows);
#: value = "file::test"
FUNC_TESTS = {
    # creation / predicates (test_ops_sweep3)
    **{n: "test_ops_sweep3.py::test_creation_ops" for n in (
        "arange", "assign", "clone", "diagflat", "empty", "empty_like",
        "eye", "full", "full_like", "linspace", "logspace", "meshgrid",
        "ones", "ones_like", "polar", "tril_indices", "triu_indices",
        "zeros", "zeros_like")},
    **{n: "test_ops_sweep3.py::test_shape_and_predicates" for n in (
        "shape", "is_empty", "is_tensor", "increment")},
    **{n: "test_ops_sweep3.py::test_random_ops_statistics" for n in (
        "bernoulli", "multinomial", "normal", "poisson", "rand",
        "rand_like", "randint", "randint_like", "randn", "randn_like",
        "randperm", "standard_normal", "uniform", "laplace",
        "standard_gamma")},
    # factorizations / search (test_ops_sweep4)
    **{n: "test_ops_sweep4.py::test_factorizations_reconstruct" for n
       in ("qr", "svd", "eigh", "eig", "eigvals", "lu", "lu_unpack",
           "svd_lowrank")},
    **{n: "test_ops_sweep4.py::test_unique_and_histogram" for n in (
        "unique", "unique_consecutive", "histogramdd")},
    **{n: "test_ops_sweep4.py::test_decode_ops" for n in (
        "viterbi_decode", "gather_tree", "top_p_sampling")},
    **{n: "test_ops_sweep4.py::test_dropout_family" for n in (
        "dropout", "dropout2d", "dropout3d", "alpha_dropout", "rrelu",
        "gumbel_softmax")},
    **{n: "test_ops_sweep4.py::test_alias_schemas" for n in (
        "floor_mod", "logsigmoid", "tanh_shrink", "swish",
        "binary_cross_entropy")},
    **{n: "test_ops_sweep4.py::test_stochastic_value_ops" for n in (
        "binomial", "dirichlet", "gaussian")},
    # dimensional variants / signal / aliases (test_ops_sweep5)
    **{n: "test_ops_sweep5.py::test_conv_transpose_variants" for n in (
        "conv1d_transpose", "conv3d_transpose")},
    **{n: "test_ops_sweep5.py::test_pool_dimensional_variants" for n
       in ("avg_pool1d", "avg_pool3d", "max_pool1d", "max_pool3d",
           "adaptive_avg_pool1d", "adaptive_avg_pool3d",
           "adaptive_max_pool1d", "adaptive_max_pool3d", "lp_pool1d",
           "lp_pool2d")},
    "max_pool2d_with_index":
        "test_ops_sweep5.py::test_max_pool_with_index_and_unpool",
    "unpool": "test_ops_sweep5.py::test_max_pool_with_index_and_unpool",
    **{n: "test_ops_sweep5.py::test_interpolate_modes_cover_interp_"
          "schemas" for n in (
        "interpolate", "upsample", "bilinear_interp", "nearest_interp",
        "bicubic_interp", "linear_interp", "trilinear_interp")},
    "layer_norm": "test_ops_sweep5.py::test_norm_layers_direct",
    "rms_norm": "test_ops_sweep5.py::test_norm_layers_direct",
    "ctc_loss": "test_ops_sweep5.py::test_ctc_loss_vs_torch",
    "margin_cross_entropy":
        "test_ops_sweep5.py::test_margin_cross_entropy",
    **{n: "test_ops_sweep5.py::test_signal_ops_vs_scipy" for n in (
        "frame", "overlap_add", "stft")},
    "householder_product":
        "test_ops_sweep5.py::test_householder_product_and_ormqr",
    "ormqr": "test_ops_sweep5.py::test_householder_product_and_ormqr",
    **{n: "test_ops_sweep5.py::test_alias_loss_schemas" for n in (
        "bce_loss", "kldiv_loss", "hinge_loss",
        "sigmoid_cross_entropy_with_logits")},
    "unfold": "test_ops_sweep5.py::test_unfold_im2col",
    "view_shape": "test_ops_sweep5.py::test_view_shape_alias",
    "shuffle_channel": "test_ops_sweep5.py::test_shuffle_channel_alias",
}

#: schemas whose oracle lives in a dedicated (non-sweep) test file
POINTERS = {
    "conv1d": "test_nn_torch_oracle.py (F.conv1d vs torch)",
    "conv2d": "test_nn_torch_oracle.py (F.conv2d vs torch)",
    "conv3d": "test_nn_torch_oracle.py (F.conv3d vs torch)",
    "conv2d_transpose": "test_nn_torch_oracle.py (vs torch)",
    "batch_norm": "test_nn_torch_oracle.py (vs torch)",
    "group_norm": "test_nn_torch_oracle.py (vs torch)",
    "instance_norm": "test_nn_torch_oracle.py (vs torch)",
    "avg_pool2d": "test_nn_torch_oracle.py (vs torch)",
    "max_pool2d": "test_nn_torch_oracle.py (vs torch)",
    "adaptive_avg_pool2d": "test_nn_torch_oracle.py (vs torch)",
    "adaptive_max_pool2d": "test_nn_torch_oracle.py (vs torch)",
    "cross_entropy": "test_nn_torch_oracle.py (vs torch)",
    "pca_lowrank": "test_sparse.py::test_pca_lowrank_reconstructs",
    "accuracy_check": "test_pp_adaptor.py (accuracy_check op tests)",
    "to_tensor": "exercised by every test in the suite "
                 "(round-trip asserted throughout)",
    "pool2d": "kernel-level name of the avg/max_pool2d APIs "
              "(test_nn_torch_oracle.py + test_ops_sweep5.py)",
    "pool3d": "kernel-level name of the avg/max_pool3d APIs "
              "(test_ops_sweep5.py::test_pool_dimensional_variants)",
}


def _schemas():
    return [m.group(1) for line in YAML.open()
            if (m := re.match(r"- op : (\S+)", line))]


def _sweep_names():
    names = set()
    for f in SWEEP_FILES:
        names |= set(re.findall(r'^\s*\("([a-z0-9_]+)"',
                                (TESTS / f).read_text(), re.M))
    return names


def test_every_schema_is_covered():
    schemas = _schemas()
    swept = _sweep_names()
    uncovered = [n for n in schemas
                 if n not in swept and n not in FUNC_TESTS
                 and n not in POINTERS]
    assert not uncovered, (
        f"{len(uncovered)} op schemas have no numeric-oracle coverage "
        f"and no manifest entry: {uncovered}")


def test_manifest_not_stale():
    """Manifest entries must not shadow real sweep coverage claims,
    and FUNC_TESTS must reference test functions that exist."""
    for name, where in FUNC_TESTS.items():
        fname, tname = where.split("::", 1)
        src = (TESTS / fname).read_text()
        assert f"def {tname.split('[')[0]}" in src, \
            f"{name}: {where} does not exist"
    for fname in SWEEP_FILES:
        assert (TESTS / fname).exists()


def test_counts():
    schemas = _schemas()
    swept = _sweep_names()
    in_tables = sum(1 for n in schemas if n in swept)
    # keep an honest record in the assertion message
    assert in_tables + len(FUNC_TESTS) + len(POINTERS) >= len(schemas), (
        len(schemas), in_tables, len(FUNC_TESTS), len(POINTERS))
