"""Op schema registry tests (SURVEY §2.2): ops.yaml ↔ op library ↔ _C_ops
conformance, and InferMeta functions vs XLA abstract evaluation.

Reference mechanism being mirrored: the yaml is the single source of truth
(paddle/phi/ops/yaml/ops.yaml) and generated surfaces must stay in sync
(python_c_gen.py); infermeta shape fns must agree with kernel semantics
(phi/infermeta tested by OpTest shape checks).
"""
import importlib

import numpy as np
import jax.numpy as jnp
import pytest

from paddle_tpu.ops.yaml.generator import generate, load_schemas, load_compat
from paddle_tpu.core import infermeta as im


@pytest.fixture(scope="module")
def schemas():
    return load_schemas()


def test_every_kernel_resolves(schemas):
    for s in schemas:
        path = s["kernel"]["func"]
        mod, fn = path.rsplit(".", 1)
        obj = getattr(importlib.import_module(mod), fn, None)
        assert callable(obj), f"kernel {path} does not resolve"


def test_inplace_variants_exist(schemas):
    for s in schemas:
        if "inplace" not in s:
            continue
        path = s["kernel"]["func"]
        mod, fn = path.rsplit(".", 1)
        obj = getattr(importlib.import_module(mod), fn + "_", None)
        assert callable(obj), f"declared inplace {fn}_ missing in {mod}"


def test_infermeta_func_resolves(schemas):
    for s in schemas:
        fname = s["infer_meta"]["func"]
        assert hasattr(im, fname), f"infermeta fn {fname} missing"


def test_generated_c_ops_up_to_date():
    import paddle_tpu
    gen = generate()
    path = importlib.import_module("paddle_tpu._C_ops").__file__
    with open(path) as f:
        assert f.read() == gen, "_C_ops.py stale: rerun generator"


def test_compat_aliases_bound():
    import paddle_tpu._C_ops as C
    for op, legacy in load_compat().items():
        assert hasattr(C, legacy), legacy
        assert getattr(C, legacy) is getattr(C, op)


def test_c_ops_callable_smoke():
    import paddle_tpu as pd
    import paddle_tpu._C_ops as C
    x = pd.Tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_allclose(C.add(x, x).numpy(), x.numpy() * 2)
    np.testing.assert_allclose(
        C.matmul(x, x.T).numpy(), x.numpy() @ x.numpy().T, rtol=1e-6)
    assert C.reshape(x, [3, 2]).shape == [3, 2]


# ---------------------------------------------------------------- infermeta

def M(shape, dtype=np.float32):
    return im.MetaTensor(shape, dtype)


def test_broadcast_shape():
    assert im.broadcast_shape((2, 1, 3), (4, 3)) == (2, 4, 3)
    with pytest.raises(ValueError):
        im.broadcast_shape((2, 3), (4,))


@pytest.mark.parametrize("a,b,kw", [
    ((2, 3), (3, 4), {}),
    ((5, 2, 3), (3, 4), {}),
    ((5, 2, 3), (5, 3, 4), {}),
    ((3,), (3, 4), {}),
    ((2, 3), (3,), {}),
    ((2, 3), (2, 4), {"transpose_x": True}),
    ((2, 3), (4, 3), {"transpose_y": True}),
])
def test_matmul_infermeta_matches_eval_shape(a, b, kw):
    got = im.matmul_infermeta(M(a), M(b), **kw)
    want = im.infer_via_eval_shape(
        lambda p, q: jnp.matmul(
            jnp.swapaxes(p, -1, -2) if kw.get("transpose_x") and p.ndim > 1
            else p,
            jnp.swapaxes(q, -1, -2) if kw.get("transpose_y") and q.ndim > 1
            else q),
        M(a), M(b))
    assert got == want


@pytest.mark.parametrize("shape,target", [
    ((2, 3, 4), (6, 4)), ((2, 3, 4), (-1,)), ((2, 3, 4), (0, -1)),
    ((6,), (2, 3)),
])
def test_reshape_infermeta(shape, target):
    got = im.reshape_infermeta(M(shape), target)
    # emulate the 0/-1 resolution numpy-side
    t = list(target)
    for i, s in enumerate(t):
        if s == 0:
            t[i] = shape[i]
    want = np.zeros(shape).reshape(t).shape
    assert got.shape == want


def test_reduce_infermeta():
    assert im.reduce_infermeta(M((2, 3, 4)), axis=1).shape == (2, 4)
    assert im.reduce_infermeta(M((2, 3, 4)), axis=(0, 2),
                               keepdim=True).shape == (1, 3, 1)
    assert im.reduce_infermeta(M((2, 3)), axis=None).shape == ()


def test_concat_split_stack():
    assert im.concat_infermeta([M((2, 3)), M((4, 3))], 0).shape == (6, 3)
    assert im.stack_infermeta([M((2, 3))] * 4, 1).shape == (2, 4, 3)
    outs = im.split_infermeta(M((6, 3)), 3, 0)
    assert [o.shape for o in outs] == [(2, 3)] * 3
    outs = im.split_infermeta(M((6, 3)), [1, 2, 3], 0)
    assert [o.shape for o in outs] == [(1, 3), (2, 3), (3, 3)]


def test_conv_pool_infermeta_match_jax():
    import jax
    x, w = M((2, 3, 16, 16)), M((8, 3, 3, 3))
    got = im.conv2d_infermeta(x, w, stride=2, padding=1)
    out = jax.eval_shape(
        lambda a, b: jax.lax.conv_general_dilated(
            a, b, (2, 2), [(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW")),
        jax.ShapeDtypeStruct(x.shape, x.dtype),
        jax.ShapeDtypeStruct(w.shape, w.dtype))
    assert got.shape == out.shape
    assert im.pool2d_infermeta(M((2, 3, 16, 16)), 2, 2).shape == (2, 3, 8, 8)


def test_elementwise_promotion():
    got = im.elementwise_infermeta(M((2, 3), np.float32),
                                   M((3,), np.float64))
    assert got.shape == (2, 3) and got.dtype == np.float64


def test_transpose_expand_tile_pad():
    assert im.transpose_infermeta(M((2, 3, 4)), (2, 0, 1)).shape == (4, 2, 3)
    assert im.expand_infermeta(M((1, 3)), (5, -1)).shape == (5, 3)
    assert im.tile_infermeta(M((2, 3)), (2,)).shape == (2, 6)
    assert im.pad_infermeta(M((2, 3)), [1, 1, 0, 2]).shape == (4, 5)


def test_embedding_gather_where():
    assert im.embedding_infermeta(M((4, 7), np.int64),
                                  M((100, 16))).shape == (4, 7, 16)
    assert im.gather_infermeta(M((5, 3)), M((7,), np.int64), 0).shape \
        == (7, 3)
    assert im.where_infermeta(M((2, 1), np.bool_), M((2, 3)),
                              M((3,))).shape == (2, 3)
