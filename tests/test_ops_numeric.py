"""Single-op numeric-oracle + finite-difference grad tests through the
OpTest harness (reference mechanism: test/legacy_test/op_test.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F

from op_test import OpTest

rng = np.random.RandomState(42)


class TestMatmul(OpTest):
    op = staticmethod(paddle.matmul)
    ref = staticmethod(lambda a, b: a @ b)
    inputs = {"x": rng.randn(4, 6).astype(np.float32),
              "y": rng.randn(6, 3).astype(np.float32)}

    def test(self):
        self.check_output()
        self.check_grad()


class TestExp(OpTest):
    op = staticmethod(paddle.exp)
    ref = staticmethod(np.exp)
    inputs = {"x": rng.randn(3, 4).astype(np.float32)}

    def test(self):
        self.check_output()
        self.check_grad()


class TestSoftmax(OpTest):
    op = staticmethod(F.softmax)
    inputs = {"x": rng.randn(3, 8).astype(np.float32)}

    @staticmethod
    def ref(x):
        e = np.exp(x - x.max(-1, keepdims=True))
        return e / e.sum(-1, keepdims=True)

    def test(self):
        self.check_output()
        self.check_grad()


class TestLogSumExp(OpTest):
    op = staticmethod(paddle.logsumexp)
    inputs = {"x": rng.randn(4, 5).astype(np.float32)}

    @staticmethod
    def ref(x):
        m = x.max()
        return m + np.log(np.exp(x - m).sum())

    def test(self):
        self.check_output()
        self.check_grad()


class TestTanh(OpTest):
    op = staticmethod(paddle.tanh)
    ref = staticmethod(np.tanh)
    inputs = {"x": rng.randn(5,).astype(np.float32)}

    def test(self):
        self.check_output()
        self.check_grad()


class TestSigmoidGrad(OpTest):
    op = staticmethod(paddle.sigmoid)
    ref = staticmethod(lambda x: 1 / (1 + np.exp(-x)))
    inputs = {"x": rng.randn(4, 4).astype(np.float32)}

    def test(self):
        self.check_output()
        self.check_grad()


class TestMeanAxis(OpTest):
    op = staticmethod(paddle.mean)
    ref = staticmethod(lambda x, axis=1, keepdim=True:
                       x.mean(axis=axis, keepdims=keepdim))
    inputs = {"x": rng.randn(3, 5).astype(np.float32)}
    attrs = {"axis": 1, "keepdim": True}

    def test(self):
        self.check_output()
        self.check_grad()


class TestConcat(OpTest):
    inputs = {"x": rng.randn(2, 3).astype(np.float32),
              "y": rng.randn(2, 3).astype(np.float32)}

    @staticmethod
    def op(x, y):
        return paddle.concat([x, y], axis=1)

    @staticmethod
    def ref(x, y):
        return np.concatenate([x, y], 1)

    def test(self):
        self.check_output()
        self.check_grad()


class TestGather(OpTest):
    inputs = {"x": rng.randn(6, 4).astype(np.float32),
              "idx": np.array([0, 3, 5], np.int64)}

    @staticmethod
    def op(x, idx):
        return paddle.gather(x, idx, axis=0)

    @staticmethod
    def ref(x, idx):
        return x[idx]

    def test(self):
        self.check_output()
        self.check_grad(grad_inputs=["x"])


class TestLayerNorm(OpTest):
    inputs = {"x": rng.randn(4, 8).astype(np.float32),
              "g": rng.rand(8).astype(np.float32) + 0.5,
              "b": rng.randn(8).astype(np.float32)}

    @staticmethod
    def op(x, g, b):
        return F.layer_norm(x, 8, g, b)

    @staticmethod
    def ref(x, g, b):
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        return (x - mu) / np.sqrt(var + 1e-5) * g + b

    rtol = 1e-4
    atol = 1e-5

    def test(self):
        self.check_output()
        self.check_grad()


class TestCrossEntropy(OpTest):
    inputs = {"logits": rng.randn(6, 5).astype(np.float32),
              "label": rng.randint(0, 5, (6,)).astype(np.int64)}

    @staticmethod
    def op(logits, label):
        return F.cross_entropy(logits, label)

    @staticmethod
    def ref(logits, label):
        m = logits.max(-1, keepdims=True)
        logp = logits - m - np.log(
            np.exp(logits - m).sum(-1, keepdims=True))
        return -logp[np.arange(len(label)), label].mean()

    def test(self):
        self.check_output()
        self.check_grad(grad_inputs=["logits"])


class TestConv2D(OpTest):
    inputs = {"x": rng.randn(1, 2, 6, 6).astype(np.float32),
              "w": rng.randn(3, 2, 3, 3).astype(np.float32)}
    attrs = {"stride": 1, "padding": 1}
    rtol = 1e-4
    atol = 1e-5

    @staticmethod
    def op(x, w, stride=1, padding=1):
        return F.conv2d(x, w, stride=stride, padding=padding)

    @staticmethod
    def ref(x, w, stride=1, padding=1):
        n, ci, h, wd = x.shape
        co, _, kh, kw = w.shape
        xp = np.pad(x, ((0, 0), (0, 0), (padding, padding),
                        (padding, padding)))
        oh = (h + 2 * padding - kh) // stride + 1
        ow = (wd + 2 * padding - kw) // stride + 1
        out = np.zeros((n, co, oh, ow), np.float64)
        for i in range(oh):
            for j in range(ow):
                patch = xp[:, :, i * stride:i * stride + kh,
                           j * stride:j * stride + kw]
                out[:, :, i, j] = np.einsum("ncij,ocij->no", patch, w)
        return out

    def test(self):
        self.check_output()
        self.check_grad()


class TestWhere(OpTest):
    inputs = {"c": rng.rand(3, 4) > 0.5,
              "x": rng.randn(3, 4).astype(np.float32),
              "y": rng.randn(3, 4).astype(np.float32)}

    @staticmethod
    def op(c, x, y):
        return paddle.where(c, x, y)

    @staticmethod
    def ref(c, x, y):
        return np.where(c, x, y)

    def test(self):
        self.check_output()
        self.check_grad(grad_inputs=["x", "y"])


class TestRsqrt(OpTest):
    op = staticmethod(paddle.rsqrt)
    ref = staticmethod(lambda x: 1.0 / np.sqrt(x))
    inputs = {"x": (rng.rand(4, 3) + 0.5).astype(np.float32)}

    def test(self):
        self.check_output()
        self.check_grad()


class TestPow(OpTest):
    op = staticmethod(lambda x: paddle.pow(x, 3.0))
    ref = staticmethod(lambda x: x ** 3.0)
    inputs = {"x": (rng.rand(3, 3) + 0.5).astype(np.float32)}

    def test(self):
        self.check_output()
        self.check_grad()


class TestCumsum(OpTest):
    op = staticmethod(paddle.cumsum)
    ref = staticmethod(lambda x, axis=1: np.cumsum(x, axis))
    inputs = {"x": rng.randn(3, 5).astype(np.float32)}
    attrs = {"axis": 1}

    def test(self):
        self.check_output()
        self.check_grad()


class TestSplitStack(OpTest):
    inputs = {"x": rng.randn(4, 6).astype(np.float32)}

    @staticmethod
    def op(x):
        a, b, c = paddle.split(x, 3, axis=1)
        return paddle.stack([a, b, c], axis=0)

    @staticmethod
    def ref(x):
        return np.stack(np.split(x, 3, 1), 0)

    def test(self):
        self.check_output()
        self.check_grad()


def test_sdpa_matches_reference():
    b, s, h, d = 2, 16, 2, 8
    q = rng.randn(b, s, h, d).astype(np.float32)
    k = rng.randn(b, s, h, d).astype(np.float32)
    v = rng.randn(b, s, h, d).astype(np.float32)
    out = F.scaled_dot_product_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        is_causal=True)
    # numpy oracle
    logits = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    mask = np.tril(np.ones((s, s), bool))
    logits = np.where(mask, logits, -1e30)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bkhd->bqhd", p, v)
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4, atol=2e-5)
