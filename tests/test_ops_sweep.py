"""Data-driven single-op numeric sweep through the OpTest harness —
forward vs numpy/scipy oracle + finite-difference grad check for the
differentiable ops (reference mechanism: test/legacy_test's ~1183
per-op test files; one table here)."""
import numpy as np
import pytest
import scipy.special as sps

import paddle_tpu as paddle
from op_test import OpTest

rng = np.random.RandomState(7)


def T(shape, dtype=np.float32, lo=-2.0, hi=2.0):
    return (rng.uniform(lo, hi, shape)).astype(dtype)


def POS(shape, dtype=np.float32):
    return rng.uniform(0.2, 3.0, shape).astype(dtype)


# (name, op, ref, inputs, attrs, check_grad)
CASES = [
    # unary math
    ("sin", paddle.sin, np.sin, {"x": T((3, 4))}, {}, True),
    ("cos", paddle.cos, np.cos, {"x": T((3, 4))}, {}, True),
    ("tan", paddle.tan, np.tan, {"x": T((3, 4), lo=-1, hi=1)}, {}, True),
    ("asin", paddle.asin, np.arcsin, {"x": T((8,), lo=-0.9, hi=0.9)},
     {}, True),
    ("acos", paddle.acos, np.arccos, {"x": T((8,), lo=-0.9, hi=0.9)},
     {}, True),
    ("atan", paddle.atan, np.arctan, {"x": T((8,))}, {}, True),
    ("sinh", paddle.sinh, np.sinh, {"x": T((8,))}, {}, True),
    ("cosh", paddle.cosh, np.cosh, {"x": T((8,))}, {}, True),
    ("asinh", paddle.asinh, np.arcsinh, {"x": T((8,))}, {}, True),
    ("acosh", paddle.acosh, np.arccosh, {"x": POS((8,)) + 1.1}, {},
     True),
    ("atanh", paddle.atanh, np.arctanh,
     {"x": T((8,), lo=-0.8, hi=0.8)}, {}, True),
    ("expm1", paddle.expm1, np.expm1, {"x": T((8,))}, {}, True),
    ("log2", paddle.log2, np.log2, {"x": POS((8,))}, {}, True),
    ("log10", paddle.log10, np.log10, {"x": POS((8,))}, {}, True),
    ("log1p", paddle.log1p, np.log1p, {"x": POS((8,))}, {}, True),
    ("rsqrt", paddle.rsqrt, lambda x: 1 / np.sqrt(x),
     {"x": POS((8,))}, {}, True),
    ("reciprocal", paddle.reciprocal, lambda x: 1 / x,
     {"x": POS((8,))}, {}, True),
    ("sigmoid", paddle.sigmoid, lambda x: 1 / (1 + np.exp(-x)),
     {"x": T((8,))}, {}, True),
    ("erf", paddle.erf, sps.erf, {"x": T((8,))}, {}, True),
    ("erfinv", paddle.erfinv, sps.erfinv,
     {"x": T((8,), lo=-0.8, hi=0.8)}, {}, True),
    ("lgamma", paddle.lgamma, sps.gammaln, {"x": POS((8,))}, {}, True),
    ("digamma", paddle.digamma, sps.digamma, {"x": POS((8,))}, {},
     True),
    ("square", paddle.square, np.square, {"x": T((8,))}, {}, True),
    ("abs", paddle.abs, np.abs, {"x": T((8,)) + 0.1}, {}, True),
    ("floor", paddle.floor, np.floor, {"x": T((8,))}, {}, False),
    ("ceil", paddle.ceil, np.ceil, {"x": T((8,))}, {}, False),
    ("round", paddle.round, np.round, {"x": T((8,))}, {}, False),
    ("trunc", paddle.trunc, np.trunc, {"x": T((8,))}, {}, False),
    ("frac", paddle.frac, lambda x: x - np.trunc(x), {"x": T((8,))},
     {}, False),
    ("sign", paddle.sign, np.sign, {"x": T((8,)) + 0.1}, {}, False),
    ("logit", paddle.logit, sps.logit,
     {"x": T((8,), lo=0.1, hi=0.9)}, {}, True),
    ("i0", paddle.i0, sps.i0, {"x": T((8,))}, {}, True),
    ("sinc", paddle.sinc, np.sinc, {"x": T((8,)) + 0.05}, {}, True),
    # binary
    ("atan2", paddle.atan2, np.arctan2,
     {"x": T((8,)), "y": POS((8,))}, {}, True),
    ("maximum", paddle.maximum, np.maximum,
     {"x": T((8,)), "y": T((8,))}, {}, True),
    ("minimum", paddle.minimum, np.minimum,
     {"x": T((8,)), "y": T((8,))}, {}, True),
    ("fmax", paddle.fmax, np.fmax, {"x": T((8,)), "y": T((8,))}, {},
     False),
    ("fmin", paddle.fmin, np.fmin, {"x": T((8,)), "y": T((8,))}, {},
     False),
    ("heaviside", paddle.heaviside, np.heaviside,
     {"x": T((8,)) + 0.1, "y": T((8,))}, {}, False),
    ("hypot", paddle.hypot, np.hypot,
     {"x": POS((8,)), "y": POS((8,))}, {}, True),
    ("copysign", paddle.copysign, np.copysign,
     {"x": T((8,)), "y": T((8,)) + 0.1}, {}, False),
    ("nextafter", paddle.nextafter, np.nextafter,
     {"x": T((8,)), "y": T((8,))}, {}, False),
    ("logaddexp", paddle.logaddexp, np.logaddexp,
     {"x": T((8,)), "y": T((8,))}, {}, True),
    ("ldexp", paddle.ldexp, np.ldexp,
     {"x": T((8,)), "y": np.array([1, 2, 0, -1, 3, 2, 1, 0],
                                  np.int32)}, {}, False),
    # reductions
    ("sum_axis", paddle.sum, lambda x, axis: np.sum(x, axis),
     {"x": T((3, 5))}, {"axis": 1}, True),
    ("mean_keep", paddle.mean, lambda x, axis, keepdim: np.mean(x, axis, keepdims=keepdim),
     {"x": T((3, 5))}, {"axis": 0, "keepdim": True}, True),
    ("prod", paddle.prod, lambda x, axis: np.prod(x, axis),
     {"x": POS((3, 4))}, {"axis": -1}, True),
    ("amax", paddle.amax, lambda x, axis: np.max(x, axis), {"x": T((3, 5))},
     {"axis": 1}, False),
    ("amin", paddle.amin, lambda x, axis: np.min(x, axis), {"x": T((3, 5))},
     {"axis": 1}, False),
    ("logsumexp_ax", paddle.logsumexp, lambda x, axis: sps.logsumexp(x, axis),
     {"x": T((3, 5))}, {"axis": 1}, True),
    ("std", paddle.std, lambda x: np.std(x, ddof=1), {"x": T((24,))},
     {}, True),
    ("var", paddle.var, lambda x: np.var(x, ddof=1), {"x": T((24,))},
     {}, True),
    ("median", paddle.median, lambda x: np.median(x),
     {"x": T((9,))}, {}, False),
    ("nansum", paddle.nansum, np.nansum,
     {"x": np.array([1.0, np.nan, 2.0], np.float32)}, {}, False),
    ("nanmean", paddle.nanmean, np.nanmean,
     {"x": np.array([1.0, np.nan, 3.0], np.float32)}, {}, False),
    ("cumsum", paddle.cumsum, lambda x, axis: np.cumsum(x, axis),
     {"x": T((4, 3))}, {"axis": 0}, True),
    ("cumprod", paddle.cumprod, lambda x, dim: np.cumprod(x, dim),
     {"x": POS((4, 3))}, {"dim": 0}, True),
    ("logcumsumexp", paddle.logcumsumexp,
     lambda x, axis: np.log(np.cumsum(np.exp(x), axis)), {"x": T((5,))},
     {"axis": 0}, True),
    # manipulation / linalg
    ("diff", paddle.diff, lambda x: np.diff(x), {"x": T((7,))}, {},
     True),
    ("kron", paddle.kron, np.kron,
     {"x": T((2, 3)), "y": T((3, 2))}, {}, True),
    ("inner", paddle.inner, np.inner,
     {"x": T((3, 4)), "y": T((5, 4))}, {}, True),
    ("outer", paddle.outer, np.outer,
     {"x": T((3,)), "y": T((4,))}, {}, True),
    ("cross", paddle.cross, lambda a, b: np.cross(a, b),
     {"x": T((4, 3)), "y": T((4, 3))}, {}, True),
    ("dot", paddle.dot, np.dot, {"x": T((6,)), "y": T((6,))}, {},
     True),
    ("trace", paddle.trace, np.trace, {"x": T((4, 4))}, {}, True),
    ("diagonal", paddle.diagonal, lambda x: np.diagonal(x, 0, 0, 1),
     {"x": T((4, 4))}, {}, True),
    ("flip", paddle.flip, lambda x, axis: np.flip(x, axis), {"x": T((3, 2))},
     {"axis": 0}, True),
    ("roll", paddle.roll, lambda x, shifts: np.roll(x, shifts), {"x": T((6,))},
     {"shifts": 2}, True),
    ("rot90", paddle.rot90, lambda x: np.rot90(x), {"x": T((3, 4))},
     {}, True),
    ("tril", paddle.tril, np.tril, {"x": T((4, 4))}, {}, True),
    ("triu", paddle.triu, np.triu, {"x": T((4, 4))}, {}, True),
    ("rad2deg", paddle.rad2deg, np.rad2deg, {"x": T((6,))}, {}, False),
    ("deg2rad", paddle.deg2rad, np.deg2rad, {"x": T((6,))}, {},
     False),
    ("nan_to_num", paddle.nan_to_num, np.nan_to_num,
     {"x": np.array([1.0, np.nan, np.inf], np.float32)}, {}, False),
    ("clip", paddle.clip, lambda x, min, max: np.clip(x, min, max),
     {"x": T((8,))}, {"min": -0.5, "max": 0.5}, True),
    ("lerp", paddle.lerp,
     lambda x, y, w: x + w * (y - x),
     {"x": T((6,)), "y": T((6,)),
      "w": np.float32(0.3)}, {}, False),
    ("matrix_power", paddle.linalg.matrix_power,
     lambda x, n: np.linalg.matrix_power(x, n), {"x": T((3, 3)) * 0.5},
     {"n": 3}, False),
    ("slogdet", paddle.linalg.slogdet,
     lambda x: np.concatenate(np.linalg.slogdet(x)[None, :])
     if False else np.stack(np.linalg.slogdet(x)),
     {"x": T((3, 3)) + 3 * np.eye(3, dtype=np.float32)}, {}, False),
]


@pytest.mark.parametrize("case", CASES, ids=[c[0] for c in CASES])
def test_op_numeric(case):
    name, op, ref, inputs, attrs, grad = case
    cls = type(f"T_{name}", (OpTest,), {
        "op": staticmethod(op), "ref": staticmethod(ref),
        "inputs": inputs, "attrs": attrs,
        "rtol": 2e-4, "atol": 1e-5,
    })
    t = cls()
    t.check_output()
    if grad:
        t.check_grad()
