"""Second data-driven single-op numeric tranche (same OpTest harness
as test_ops_sweep.py; reference mechanism test/legacy_test per-op
files): special functions, search/sort, indexing, linalg solves,
logic/bitwise, and histogram-family ops vs numpy/scipy oracles."""
import numpy as np
import pytest
import scipy.special as sps

import paddle_tpu as paddle
from op_test import OpTest

rng = np.random.RandomState(11)


def T(shape, dtype=np.float32, lo=-2.0, hi=2.0):
    return (rng.uniform(lo, hi, shape)).astype(dtype)


def POS(shape, dtype=np.float32):
    return rng.uniform(0.2, 3.0, shape).astype(dtype)


def SPD(n):
    a = T((n, n)) * 0.3
    return (a @ a.T + n * np.eye(n, dtype=np.float32))


I32 = lambda *v: np.asarray(v, np.int32)


# (name, op, ref, inputs, attrs, check_grad)
CASES = [
    # special functions
    ("i1", paddle.i1, sps.i1, {"x": T((8,))}, {}, True),
    ("i0e", paddle.i0e, sps.i0e, {"x": T((8,))}, {}, True),
    ("i1e", paddle.i1e, sps.i1e, {"x": T((8,))}, {}, True),
    ("polygamma", paddle.polygamma,
     lambda x, n: sps.polygamma(n, x), {"x": POS((6,))}, {"n": 1},
     False),
    ("gammaln", paddle.gammaln, sps.gammaln, {"x": POS((8,))}, {},
     True),
    ("exp", paddle.exp, np.exp, {"x": T((8,))}, {}, True),
    ("tanh", paddle.tanh, np.tanh, {"x": T((8,))}, {}, True),
    ("pow", paddle.pow, lambda x, y: np.power(x, y),
     {"x": POS((6,))}, {"y": 2.5}, True),
    # sorting / searching
    ("sort", paddle.sort, lambda x, axis: np.sort(x, axis),
     {"x": T((4, 5))}, {"axis": 1}, True),
    ("argsort", paddle.argsort, lambda x, axis: np.argsort(
        x, axis, kind="stable"), {"x": T((4, 5))}, {"axis": 1}, False),
    ("argmax", paddle.argmax, lambda x, axis: np.argmax(x, axis),
     {"x": T((4, 5))}, {"axis": 1}, False),
    ("argmin", paddle.argmin, lambda x, axis: np.argmin(x, axis),
     {"x": T((4, 5))}, {"axis": 0}, False),
    ("topk", lambda x, k: paddle.topk(x, k)[0],
     lambda x, k: np.sort(x, -1)[..., ::-1][..., :k],
     {"x": T((3, 7))}, {"k": 3}, True),
    ("kthvalue", lambda x, k: paddle.kthvalue(x, k)[0],
     lambda x, k: np.sort(x, -1)[..., k - 1],
     {"x": T((3, 7))}, {"k": 2}, False),
    ("mode", lambda x: paddle.mode(x)[0],
     lambda x: np.array([1., 2.], np.float32),
     {"x": np.array([[1., 1., 3.], [2., 2., 0.]], np.float32)}, {},
     False),
    ("searchsorted", paddle.searchsorted,
     lambda s, v: np.searchsorted(s, v).astype(np.int64),
     {"sorted_sequence": np.sort(T((8,))), "values": T((5,))}, {},
     False),
    ("bucketize", paddle.bucketize,
     lambda x, s: np.searchsorted(s, x).astype(np.int64),
     {"x": T((6,)), "sorted_sequence": np.sort(T((5,)))}, {}, False),
    # indexing / gather-scatter
    ("index_select", paddle.index_select,
     lambda x, index, axis: np.take(x, index, axis),
     {"x": T((4, 5)), "index": I32(0, 2, 2)}, {"axis": 0}, True),
    ("take_along_axis", paddle.take_along_axis,
     lambda arr, indices, axis: np.take_along_axis(
         arr, indices.astype(np.int64), axis),
     {"arr": T((3, 4)), "indices": rng.randint(0, 4, (3, 2))
      .astype(np.int64)}, {"axis": 1}, True),
    ("gather", paddle.gather,
     lambda x, index: np.take(x, index, 0),
     {"x": T((5, 3)), "index": I32(1, 3)}, {}, True),
    ("gather_nd", paddle.gather_nd,
     lambda x, index: x[tuple(index.T)],
     {"x": T((4, 3)), "index": np.array([[0], [2]], np.int64)}, {},
     True),
    ("scatter", paddle.scatter,
     lambda x, index, updates: _scatter_ref(x, index, updates),
     {"x": T((5, 3)), "index": I32(1, 3),
      "updates": T((2, 3))}, {}, False),
    ("index_add",
     lambda x, index, value, axis: paddle.index_add(x, index, axis,
                                                    value),
     lambda x, index, value, axis: _index_add_ref(x, index, value),
     {"x": T((5, 3)), "index": I32(0, 2), "value": T((2, 3))},
     {"axis": 0}, False),
    ("take", paddle.take, lambda x, index: np.take(x, index),
     {"x": T((4, 3)), "index": I32(0, 5, 11)}, {}, False),
    ("repeat_interleave", paddle.repeat_interleave,
     lambda x, repeats, axis: np.repeat(x, repeats, axis),
     {"x": T((3, 2))}, {"repeats": 2, "axis": 0}, True),
    ("tile", paddle.tile, lambda x, repeat_times: np.tile(
        x, repeat_times), {"x": T((2, 3))}, {"repeat_times": (2, 1)},
     True),
    ("diag", paddle.diag, np.diag, {"x": T((4,))}, {}, True),
    ("diag_embed", paddle.diag_embed,
     lambda x: np.stack([np.diag(r) for r in x]),
     {"x": T((3, 4))}, {}, False),
    ("flatten", paddle.flatten, lambda x: x.reshape(-1),
     {"x": T((2, 3, 4))}, {}, True),
    # linalg
    ("solve", paddle.linalg.solve, np.linalg.solve,
     {"x": SPD(4), "y": T((4, 2))}, {}, True),
    ("cholesky", paddle.linalg.cholesky,
     lambda x: np.linalg.cholesky(x), {"x": SPD(4)}, {}, False),
    ("triangular_solve", paddle.linalg.triangular_solve,
     lambda x, y: np.linalg.solve(np.triu(x), y),
     {"x": SPD(3), "y": T((3, 2))}, {}, False),
    ("det", paddle.linalg.det, np.linalg.det, {"x": SPD(3)}, {},
     True),
    ("inv", paddle.linalg.inv, np.linalg.inv, {"x": SPD(3)}, {},
     True),
    ("pinv", paddle.linalg.pinv, np.linalg.pinv, {"x": T((4, 3))},
     {}, False),
    ("eigvalsh", lambda x: paddle.linalg.eigvalsh(x),
     lambda x: np.linalg.eigvalsh(x), {"x": SPD(4)}, {}, False),
    ("matrix_rank", paddle.linalg.matrix_rank,
     lambda x: np.int64(np.linalg.matrix_rank(x)), {"x": SPD(3)}, {},
     False),
    ("norm_fro", paddle.linalg.norm, lambda x: np.linalg.norm(x),
     {"x": T((3, 4))}, {}, True),
    ("cond", paddle.linalg.cond,
     lambda x: np.float32(np.linalg.cond(x)), {"x": SPD(3)}, {},
     False),
    ("matmul", paddle.matmul, np.matmul,
     {"x": T((3, 4)), "y": T((4, 5))}, {}, True),
    ("bmm", paddle.bmm, np.matmul,
     {"x": T((2, 3, 4)), "y": T((2, 4, 2))}, {}, True),
    ("mv", paddle.mv, np.matmul, {"x": T((3, 4)), "y": T((4,))}, {},
     True),
    ("dist", paddle.dist,
     lambda x, y, p: np.float32(np.linalg.norm((x - y).ravel(), p)),
     {"x": T((3, 4)), "y": T((3, 4))}, {"p": 2}, True),
    # logic / comparison / bitwise
    ("isclose", paddle.isclose, np.isclose,
     {"x": T((6,)), "y": T((6,))}, {}, False),
    ("equal", paddle.equal, np.equal,
     {"x": I32(1, 2, 3), "y": I32(1, 0, 3)}, {}, False),
    ("greater_than", paddle.greater_than, np.greater,
     {"x": T((6,)), "y": T((6,))}, {}, False),
    ("logical_and", paddle.logical_and, np.logical_and,
     {"x": np.array([True, False, True]),
      "y": np.array([True, True, False])}, {}, False),
    ("logical_xor", paddle.logical_xor, np.logical_xor,
     {"x": np.array([True, False, True]),
      "y": np.array([True, True, False])}, {}, False),
    ("bitwise_and", paddle.bitwise_and, np.bitwise_and,
     {"x": I32(5, 6, 7), "y": I32(3, 3, 3)}, {}, False),
    ("bitwise_xor", paddle.bitwise_xor, np.bitwise_xor,
     {"x": I32(5, 6, 7), "y": I32(3, 3, 3)}, {}, False),
    ("isfinite", paddle.isfinite, np.isfinite,
     {"x": np.array([1.0, np.inf, np.nan], np.float32)}, {}, False),
    ("isnan", paddle.isnan, np.isnan,
     {"x": np.array([1.0, np.inf, np.nan], np.float32)}, {}, False),
    # histogram family / misc
    ("bincount", paddle.bincount,
     lambda x: np.bincount(x).astype(np.int64),
     {"x": np.array([0, 1, 1, 3], np.int64)}, {}, False),
    ("histogram", lambda x: paddle.histogram(x, bins=4, min=0, max=4),
     lambda x: np.histogram(x, bins=4, range=(0, 4))[0].astype(
         np.int64), {"x": T((20,), lo=0, hi=4)}, {}, False),
    ("cummax", lambda x: paddle.cummax(x, axis=0)[0],
     lambda x: np.maximum.accumulate(x, 0), {"x": T((6,))}, {}, True),
    ("cummin", lambda x: paddle.cummin(x, axis=0)[0],
     lambda x: np.minimum.accumulate(x, 0), {"x": T((6,))}, {}, True),
    ("vander", paddle.vander, lambda x: np.vander(x),
     {"x": T((4,))}, {}, False),
    ("trapezoid", paddle.trapezoid,
     lambda y, dx: np.float32(np.trapezoid(y, dx=dx)
                              if hasattr(np, "trapezoid")
                              else np.trapz(y, dx=dx)),
     {"y": T((7,))}, {"dx": 0.5}, True),
    ("pdist_like_cdist", paddle.cdist,
     lambda x, y: _cdist_ref(x, y),
     {"x": T((3, 4)), "y": T((5, 4))}, {}, False),
]


def _scatter_ref(x, index, updates):
    out = x.copy()
    out[index] = updates
    return out


def _index_add_ref(x, index, value):
    out = x.copy()
    np.add.at(out, index, value)
    return out


def _cdist_ref(x, y):
    return np.sqrt(((x[:, None, :] - y[None, :, :]) ** 2).sum(-1))


@pytest.mark.parametrize("case", CASES, ids=[c[0] for c in CASES])
def test_op_numeric2(case):
    name, op, ref, inputs, attrs, grad = case
    cls = type(f"T_{name}", (OpTest,), {
        "op": staticmethod(op), "ref": staticmethod(ref),
        "inputs": inputs, "attrs": attrs,
        "rtol": 2e-4, "atol": 1e-5,
    })
    t = cls()
    t.check_output()
    if grad:
        t.check_grad()
