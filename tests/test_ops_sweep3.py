"""Third op-oracle sweep tranche (VERDICT r1 item 5): elementwise
arithmetic, reductions, manipulation, creation, logic/compare,
activations and losses — numpy/scipy/torch oracles through the OpTest
harness (reference mechanism: test/legacy_test per-op files)."""
import numpy as np
import pytest
import torch
import torch.nn.functional as tF

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from op_test import OpTest

rng = np.random.RandomState(11)


def T(shape, dtype=np.float32, lo=-2.0, hi=2.0):
    return rng.uniform(lo, hi, shape).astype(dtype)


def POS(shape, dtype=np.float32):
    return rng.uniform(0.2, 3.0, shape).astype(dtype)


def I(shape, hi=5, dtype=np.int32):
    return rng.randint(0, hi, shape).astype(dtype)


def _t(fn):
    """Wrap a torch functional as a numpy oracle."""
    def ref(*arrays, **kw):
        ts = [torch.tensor(a) for a in arrays]
        out = fn(*ts, **kw)
        return out.numpy() if isinstance(out, torch.Tensor) else \
            [o.numpy() for o in out]
    return ref


# (name, op, ref, inputs, attrs, check_grad)
CASES = [
    # ---- elementwise arithmetic
    ("add", paddle.add, np.add, {"x": T((3, 4)), "y": T((3, 4))}, {},
     True),
    ("subtract", paddle.subtract, np.subtract,
     {"x": T((3, 4)), "y": T((3, 4))}, {}, True),
    ("multiply", paddle.multiply, np.multiply,
     {"x": T((3, 4)), "y": T((3, 4))}, {}, True),
    ("divide", paddle.divide, np.divide,
     {"x": T((3, 4)), "y": POS((3, 4))}, {}, True),
    ("floor_divide", paddle.floor_divide, np.floor_divide,
     {"x": I((8,), 20), "y": I((8,), 6) + 1}, {}, False),
    ("mod", paddle.mod, np.mod, {"x": T((8,)), "y": POS((8,))}, {},
     False),
    ("remainder", paddle.remainder, np.mod,
     {"x": I((8,), 17), "y": I((8,), 5) + 1}, {}, False),
    ("fmod", paddle.fmod, np.fmod, {"x": T((8,)), "y": POS((8,))}, {},
     False),
    ("gcd", paddle.gcd, np.gcd, {"x": I((8,), 24), "y": I((8,), 18)},
     {}, False),
    ("lcm", paddle.lcm, np.lcm, {"x": I((8,), 7) + 1,
                                 "y": I((8,), 5) + 1}, {}, False),
    ("neg", paddle.neg, np.negative, {"x": T((8,))}, {}, True),
    ("scale", paddle.scale, lambda x, scale=2.0, bias=1.0:
     x * scale + bias, {"x": T((8,))},
     {"scale": 2.0, "bias": 1.0}, True),
    ("log", paddle.log, np.log, {"x": POS((8,))}, {}, True),
    ("sqrt", paddle.sqrt, np.sqrt, {"x": POS((8,))}, {}, True),
    ("stanh", paddle.stanh, lambda x, scale_a=0.67, scale_b=1.7159:
     scale_b * np.tanh(scale_a * x), {"x": T((8,))}, {}, True),
    ("logsumexp", paddle.logsumexp,
     lambda x, axis=-1: np.log(np.exp(x).sum(axis)),
     {"x": T((3, 5))}, {"axis": -1}, True),
    # ---- reductions
    ("sum", paddle.sum, lambda x, axis=1: x.sum(axis),
     {"x": T((3, 4))}, {"axis": 1}, True),
    ("mean", paddle.mean, lambda x, axis=0: x.mean(axis),
     {"x": T((3, 4))}, {"axis": 0}, True),
    ("max", paddle.max, lambda x, axis=1: x.max(axis),
     {"x": T((3, 4))}, {"axis": 1}, True),
    ("min", paddle.min, lambda x, axis=1: x.min(axis),
     {"x": T((3, 4))}, {"axis": 1}, True),
    ("count_nonzero", paddle.count_nonzero,
     lambda x: np.count_nonzero(x),
     {"x": (T((3, 4)) > 0.5).astype(np.float32)}, {}, False),
    ("all", paddle.all, lambda x, axis=1: x.all(axis),
     {"x": T((3, 4)) > -1.5}, {"axis": 1}, False),
    ("any", paddle.any, lambda x, axis=1: x.any(axis),
     {"x": T((3, 4)) > 1.5}, {"axis": 1}, False),
    # ---- compare / logic / bitwise
    ("allclose", paddle.allclose, np.allclose,
     {"x": T((6,)), "y": T((6,))}, {}, False),
    ("equal_all", paddle.equal_all, np.array_equal,
     {"x": I((6,)), "y": I((6,))}, {}, False),
    ("greater_equal", paddle.greater_equal, np.greater_equal,
     {"x": T((8,)), "y": T((8,))}, {}, False),
    ("less_equal", paddle.less_equal, np.less_equal,
     {"x": T((8,)), "y": T((8,))}, {}, False),
    ("less_than", paddle.less_than, np.less,
     {"x": T((8,)), "y": T((8,))}, {}, False),
    ("not_equal", paddle.not_equal, np.not_equal,
     {"x": I((8,)), "y": I((8,))}, {}, False),
    ("logical_not", paddle.logical_not, np.logical_not,
     {"x": I((8,), 2).astype(bool)}, {}, False),
    ("logical_or", paddle.logical_or, np.logical_or,
     {"x": I((8,), 2).astype(bool), "y": I((8,), 2).astype(bool)},
     {}, False),
    ("bitwise_not", paddle.bitwise_not, np.bitwise_not,
     {"x": I((8,), 100)}, {}, False),
    ("bitwise_or", paddle.bitwise_or, np.bitwise_or,
     {"x": I((8,), 100), "y": I((8,), 100)}, {}, False),
    ("bitwise_left_shift", paddle.bitwise_left_shift, np.left_shift,
     {"x": I((8,), 100), "y": I((8,), 4)}, {}, False),
    ("bitwise_right_shift", paddle.bitwise_right_shift, np.right_shift,
     {"x": I((8,), 100), "y": I((8,), 4)}, {}, False),
    ("isinf", paddle.isinf, np.isinf,
     {"x": np.array([1.0, np.inf, -np.inf, np.nan], np.float32)}, {},
     False),
    ("isposinf", paddle.isposinf, np.isposinf,
     {"x": np.array([1.0, np.inf, -np.inf], np.float32)}, {}, False),
    ("isneginf", paddle.isneginf, np.isneginf,
     {"x": np.array([1.0, np.inf, -np.inf], np.float32)}, {}, False),
    ("isreal", paddle.isreal, np.isreal,
     {"x": (T((4,)) + 1j * (I((4,), 2) * 1.0)).astype(np.complex64)},
     {}, False),
    # ---- complex
    ("conj", paddle.conj, np.conj,
     {"x": (T((4,)) + 1j * T((4,))).astype(np.complex64)}, {}, False),
    ("real", paddle.real, np.real,
     {"x": (T((4,)) + 1j * T((4,))).astype(np.complex64)}, {}, False),
    ("imag", paddle.imag, np.imag,
     {"x": (T((4,)) + 1j * T((4,))).astype(np.complex64)}, {}, False),
    ("angle", paddle.angle, np.angle,
     {"x": (T((4,)) + 1j * T((4,))).astype(np.complex64)}, {}, False),
    ("complex", paddle.complex, lambda re, im: re + 1j * im,
     {"real": T((4,)), "imag": T((4,))}, {}, False),
    ("as_complex", paddle.as_complex,
     lambda x: x[..., 0] + 1j * x[..., 1], {"x": T((4, 2))}, {},
     False),
    ("as_real", paddle.as_real,
     lambda x: np.stack([x.real, x.imag], -1),
     {"x": (T((4,)) + 1j * T((4,))).astype(np.complex64)}, {}, False),
    # ---- manipulation
    ("cast", paddle.cast, lambda x, dtype="float64":
     x.astype(np.float64), {"x": T((4,))}, {"dtype": "float64"},
     False),
    ("concat", lambda x, y: paddle.concat([x, y], axis=0),
     lambda x, y: np.concatenate([x, y], 0),
     {"x": T((2, 3)), "y": T((2, 3))}, {}, True),
    ("stack", lambda x, y: paddle.stack([x, y], axis=1),
     lambda x, y: np.stack([x, y], 1),
     {"x": T((2, 3)), "y": T((2, 3))}, {}, True),
    ("hstack", lambda x, y: paddle.hstack([x, y]),
     lambda x, y: np.hstack([x, y]),
     {"x": T((2, 3)), "y": T((2, 3))}, {}, False),
    ("vstack", lambda x, y: paddle.vstack([x, y]),
     lambda x, y: np.vstack([x, y]),
     {"x": T((2, 3)), "y": T((2, 3))}, {}, False),
    ("dstack", lambda x, y: paddle.dstack([x, y]),
     lambda x, y: np.dstack([x, y]),
     {"x": T((2, 3)), "y": T((2, 3))}, {}, False),
    ("chunk", lambda x: paddle.chunk(x, 2, axis=1)[1],
     lambda x: np.split(x, 2, 1)[1], {"x": T((2, 6))}, {}, True),
    ("split", lambda x: paddle.split(x, [2, 4], axis=1)[1],
     lambda x: np.split(x, [2], 1)[1], {"x": T((2, 6))}, {}, True),
    ("tensor_split", lambda x: paddle.tensor_split(x, 3)[0],
     lambda x: np.array_split(x, 3)[0], {"x": T((7, 2))}, {}, False),
    ("squeeze", paddle.squeeze, np.squeeze, {"x": T((2, 1, 3))}, {},
     True),
    ("unsqueeze", lambda x: paddle.unsqueeze(x, 1),
     lambda x: np.expand_dims(x, 1), {"x": T((2, 3))}, {}, True),
    ("reshape", lambda x: paddle.reshape(x, [3, 2]),
     lambda x: x.reshape(3, 2), {"x": T((2, 3))}, {}, True),
    ("transpose", lambda x: paddle.transpose(x, [1, 0]),
     lambda x: x.transpose(1, 0), {"x": T((2, 3))}, {}, True),
    ("swapaxes", lambda x: paddle.swapaxes(x, 0, 2),
     lambda x: np.swapaxes(x, 0, 2), {"x": T((2, 3, 4))}, {}, False),
    ("moveaxis", lambda x: paddle.moveaxis(x, 0, 2),
     lambda x: np.moveaxis(x, 0, 2), {"x": T((2, 3, 4))}, {}, False),
    ("t", paddle.t, np.transpose, {"x": T((2, 3))}, {}, True),
    ("expand", lambda x: paddle.expand(x, [3, 4]),
     lambda x: np.broadcast_to(x, (3, 4)), {"x": T((1, 4))}, {},
     False),
    ("broadcast_to", lambda x: paddle.broadcast_to(x, [3, 4]),
     lambda x: np.broadcast_to(x, (3, 4)), {"x": T((1, 4))}, {},
     False),
    ("expand_as", lambda x, y: paddle.expand_as(x, y),
     lambda x, y: np.broadcast_to(x, y.shape),
     {"x": T((1, 4)), "y": T((3, 4))}, {}, False),
    ("pad", lambda x: paddle.nn.functional.pad(
        x, [1, 2], mode="constant", value=0.5),
     lambda x: np.pad(x, ((0, 0), (1, 2)), constant_values=0.5),
     {"x": T((2, 3))}, {}, False),
    ("where", paddle.where,
     lambda c, x, y: np.where(c, x, y),
     {"condition": T((8,)) > 0, "x": T((8,)), "y": T((8,))}, {},
     False),
    ("masked_select", paddle.masked_select,
     lambda x, m: x[m], {"x": T((8,)), "mask": T((8,)) > 0}, {},
     False),
    ("masked_fill", paddle.masked_fill,
     lambda x, m, value=9.0: np.where(m, value, x),
     {"x": T((8,)), "mask": T((8,)) > 0}, {"value": 9.0}, False),
    ("index_sample", paddle.index_sample,
     lambda x, idx: np.take_along_axis(x, idx, 1),
     {"x": T((3, 5)), "index": I((3, 2), 5)}, {}, False),
    ("index_fill", lambda x, idx: paddle.index_fill(x, idx, 0, 7.0),
     lambda x, idx: _np_index_fill(x, idx),
     {"x": T((4, 3)), "index": np.array([0, 2], np.int64)}, {},
     False),
    ("put_along_axis", lambda x, idx, v:
     paddle.put_along_axis(x, idx, v, 1),
     lambda x, idx, v: _np_put_along(x, idx, v),
     {"arr": T((3, 5)), "indices": I((3, 2), 5).astype(np.int64),
      "values": T((3, 2))}, {}, False),
    ("one_hot", lambda x: paddle.nn.functional.one_hot(x, 6),
     lambda x: np.eye(6, dtype=np.float32)[x],
     {"x": I((5,), 6).astype(np.int64)}, {}, False),
    ("unbind", lambda x: paddle.unbind(x, 0)[1],
     lambda x: x[1], {"x": T((3, 4))}, {}, False),
    ("unstack", lambda x: paddle.unstack(x, 0)[0],
     lambda x: x[0], {"x": T((3, 4))}, {}, False),
    ("numel", paddle.numel, lambda x: np.asarray(x.size),
     {"x": T((3, 4))}, {}, False),
    ("flip", lambda x: paddle.flip(x, [1]),
     lambda x: np.flip(x, 1), {"x": T((2, 3))}, {}, False),
    ("fill_diagonal", lambda x: x.clone().fill_diagonal_(5.0),
     lambda x: _np_fill_diag(x.copy()), {"x": T((4, 4))}, {}, False),
    ("tensordot", lambda x, y: paddle.tensordot(x, y, axes=2),
     lambda x, y: np.tensordot(x, y, 2),
     {"x": T((2, 3, 4)), "y": T((3, 4, 5))}, {}, False),
    ("multiplex", lambda a, b, idx: paddle.multiplex([a, b], idx),
     lambda a, b, idx: np.stack([a, b])[idx[:, 0],
                                        np.arange(a.shape[0])],
     {"a": T((4, 3)), "b": T((4, 3)),
      "index": I((4, 1), 2).astype(np.int32)}, {}, False),
    ("atleast_1d", paddle.atleast_1d, np.atleast_1d,
     {"x": np.float32(3.0).reshape(())}, {}, False),
    ("atleast_2d", paddle.atleast_2d, np.atleast_2d,
     {"x": T((3,))}, {}, False),
    ("atleast_3d", paddle.atleast_3d, np.atleast_3d,
     {"x": T((3, 2))}, {}, False),
    ("broadcast_tensors",
     lambda x, y: paddle.broadcast_tensors([x, y])[0],
     lambda x, y: np.broadcast_arrays(x, y)[0],
     {"x": T((1, 3)), "y": T((2, 1))}, {}, False),
    # ---- activations (torch oracle)
    ("relu", F.relu, _t(tF.relu), {"x": T((8,))}, {}, True),
    ("relu6", F.relu6, _t(tF.relu6), {"x": T((8,), lo=-8, hi=8)}, {}, True),
    ("elu", F.elu, _t(tF.elu), {"x": T((8,))}, {}, True),
    ("celu", F.celu, _t(tF.celu), {"x": T((8,))}, {}, True),
    ("selu", F.selu, _t(tF.selu), {"x": T((8,))}, {}, True),
    ("silu", F.silu, _t(tF.silu), {"x": T((8,))}, {}, True),
    ("gelu", F.gelu, _t(tF.gelu), {"x": T((8,))}, {}, True),
    ("mish", F.mish, _t(tF.mish), {"x": T((8,))}, {}, True),
    ("glu", F.glu, _t(tF.glu), {"x": T((4, 6))}, {}, True),
    ("hardshrink", F.hardshrink, _t(tF.hardshrink), {"x": T((8,))},
     {}, True),
    ("softshrink", F.softshrink, _t(tF.softshrink), {"x": T((8,))},
     {}, True),
    ("hardsigmoid", F.hardsigmoid,
     lambda x: np.clip(x / 6 + 0.5, 0, 1), {"x": T((8,), lo=-8, hi=8)}, {},
     True),
    ("hardswish", F.hardswish, _t(tF.hardswish), {"x": T((8,), lo=-8, hi=8)},
     {}, True),
    ("hardtanh", F.hardtanh, _t(tF.hardtanh), {"x": T((8,), lo=-3, hi=3)},
     {}, True),
    ("leaky_relu", F.leaky_relu,
     lambda x: np.where(x >= 0, x, 0.01 * x), {"x": T((8,))}, {},
     True),
    ("log_sigmoid", F.log_sigmoid, _t(tF.logsigmoid), {"x": T((8,))},
     {}, True),
    ("log_softmax", lambda x: F.log_softmax(x, axis=-1),
     lambda x: _t(tF.log_softmax)(x, dim=-1), {"x": T((3, 5))}, {},
     True),
    ("softmax", lambda x: F.softmax(x, axis=-1),
     lambda x: _t(tF.softmax)(x, dim=-1), {"x": T((3, 5))}, {}, True),
    ("softplus", F.softplus, _t(tF.softplus), {"x": T((8,))}, {},
     True),
    ("softsign", F.softsign, _t(tF.softsign), {"x": T((8,))}, {},
     True),
    ("tanhshrink", F.tanhshrink, _t(tF.tanhshrink), {"x": T((8,))},
     {}, True),
    ("thresholded_relu", F.thresholded_relu,
     lambda x, threshold=1.0: np.where(x > threshold, x, 0.0),
     {"x": T((8,))}, {}, True),
    ("prelu", F.prelu,
     lambda x, w: np.where(x >= 0, x, w * x),
     {"x": T((2, 3)), "weight": np.array([0.25], np.float32)}, {},
     True),
    ("maxout", lambda x: F.maxout(x, groups=2),
     lambda x: _np_maxout(x, 2),
     {"x": T((2, 4, 5))}, {}, False),
    ("swiglu", F.swiglu if hasattr(F, "swiglu") else
     paddle.incubate.nn.functional.swiglu,
     lambda x, y: (x / (1 + np.exp(-x))) * y,
     {"x": T((4, 6)), "y": T((4, 6))}, {}, True),
    # ---- losses
    ("mse_loss", F.mse_loss, _t(tF.mse_loss),
     {"input": T((4, 3)), "label": T((4, 3))}, {}, True),
    ("l1_loss", F.l1_loss, _t(tF.l1_loss),
     {"input": T((4, 3)), "label": T((4, 3))}, {}, True),
    ("smooth_l1_loss", F.smooth_l1_loss, _t(tF.smooth_l1_loss),
     {"input": T((4, 3)), "label": T((4, 3))}, {}, True),
    ("huber_loss", lambda x, y: F.smooth_l1_loss(x, y, delta=1.0),
     _t(tF.huber_loss), {"input": T((4, 3)), "label": T((4, 3))}, {},
     True),
    ("binary_cross_entropy_with_logits",
     F.binary_cross_entropy_with_logits,
     _t(tF.binary_cross_entropy_with_logits),
     {"logit": T((4, 3)), "label": I((4, 3), 2).astype(np.float32)},
     {}, True),
    ("nll_loss", F.nll_loss, lambda x, t: -x[np.arange(len(t)),
                                             t].mean(),
     {"input": np.log(POS((5, 4)) / POS((5, 4)).sum(1, keepdims=True)),
      "label": I((5,), 4).astype(np.int64)}, {}, True),
    ("soft_margin_loss", F.soft_margin_loss, _t(tF.soft_margin_loss),
     {"input": T((4, 3)),
      "label": (I((4, 3), 2) * 2 - 1).astype(np.float32)}, {}, True),
    ("margin_ranking_loss",
     lambda a, b, c: F.margin_ranking_loss(a, b, c),
     lambda a, b, c: _t(tF.margin_ranking_loss)(a, b, c),
     {"input": T((6,)), "other": T((6,)),
      "label": (I((6,), 2) * 2 - 1).astype(np.float32)}, {}, True),
    ("square_error_cost", F.square_error_cost,
     lambda x, y: (x - y) ** 2,
     {"input": T((4, 3)), "label": T((4, 3))}, {}, True),
    ("log_loss", F.log_loss,
     lambda p, l, epsilon=1e-4: -l * np.log(p + epsilon)
     - (1 - l) * np.log(1 - p + epsilon),
     {"input": rng.uniform(0.1, 0.9, (4, 1)).astype(np.float32),
      "label": I((4, 1), 2).astype(np.float32)}, {}, True),
    ("kl_div", lambda x, y: F.kl_div(x, y, reduction="mean"),
     lambda x, y: _t(tF.kl_div)(x, y, reduction="mean"),
     {"input": np.log(POS((4, 3))),
      "label": POS((4, 3)) / POS((4, 3)).sum()}, {}, True),
    ("sigmoid_focal_loss",
     lambda x, y: F.sigmoid_focal_loss(x, y, reduction="mean"),
     _np_focal := lambda x, y, gamma=2.0, alpha=0.25: (
         -(y * alpha * ((1 - 1 / (1 + np.exp(-x))) ** gamma)
           * np.log(1 / (1 + np.exp(-x)))
           + (1 - y) * (1 - alpha) * ((1 / (1 + np.exp(-x))) ** gamma)
           * np.log(1 - 1 / (1 + np.exp(-x))))).mean(),
     {"logit": T((6,)), "label": I((6,), 2).astype(np.float32)}, {},
     True),
    ("dice_loss", F.dice_loss,
     lambda x, l: np.mean(
         1 - (2 * (x * np.eye(3, dtype=np.float32)[l[..., 0]])
              .sum(-1) + 1e-5) /
         (x.sum(-1) + np.eye(3, dtype=np.float32)[l[..., 0]].sum(-1)
          + 1e-5)),
     {"input": POS((5, 3)) / POS((5, 3)).sum(1, keepdims=True),
      "label": I((5, 1), 3).astype(np.int64)}, {}, False),
]


def _np_index_fill(x, idx):
    out = x.copy()
    out[idx] = 7.0
    return out


def _np_put_along(x, idx, v):
    out = x.copy()
    np.put_along_axis(out, idx, v, 1)
    return out


def _np_fill_diag(x):
    np.fill_diagonal(x, 5.0)
    return x


def _np_maxout(x, groups):
    # reference formula (activation.py maxout docs): output channel i
    # = max over the CONTIGUOUS group x[:, i*groups : (i+1)*groups]
    n, c, rest = x.shape[0], x.shape[1], x.shape[2:]
    return x.reshape((n, c // groups, groups) + rest).max(2)


@pytest.mark.parametrize(
    "name,op,ref,inputs,attrs,grad", CASES,
    ids=[c[0] for c in CASES])
def test_op_oracle(name, op, ref, inputs, attrs, grad):
    class Case(OpTest):
        pass

    Case.op = staticmethod(op)
    Case.ref = staticmethod(ref)
    Case.inputs = inputs
    Case.attrs = attrs
    t = Case()
    t.check_output()
    if grad:
        t.check_grad()


# ---- creation ops: value/shape oracles (not OpTest-shaped) ----------
def test_creation_ops():
    np.testing.assert_array_equal(paddle.arange(2, 10, 3).numpy(),
                                  np.arange(2, 10, 3))
    np.testing.assert_array_equal(paddle.eye(3, 4).numpy(),
                                  np.eye(3, 4, dtype=np.float32))
    np.testing.assert_array_equal(
        paddle.full([2, 3], 7.0).numpy(), np.full((2, 3), 7.0,
                                                  np.float32))
    x = paddle.to_tensor(T((2, 3)))
    np.testing.assert_array_equal(paddle.full_like(x, 2.0).numpy(),
                                  np.full((2, 3), 2.0, np.float32))
    np.testing.assert_array_equal(paddle.ones([2]).numpy(),
                                  np.ones(2, np.float32))
    np.testing.assert_array_equal(paddle.zeros_like(x).numpy(),
                                  np.zeros((2, 3), np.float32))
    np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(),
                               np.linspace(0, 1, 5, dtype=np.float32))
    np.testing.assert_allclose(
        paddle.logspace(0, 2, 3).numpy(),
        np.logspace(0, 2, 3, dtype=np.float32), rtol=1e-6)
    np.testing.assert_array_equal(
        paddle.diagflat(paddle.to_tensor([1.0, 2.0])).numpy(),
        np.diagflat([1.0, 2.0]).astype(np.float32))
    a, b = np.tril_indices(4, -1)
    got = paddle.tril_indices(4, 4, -1).numpy()
    np.testing.assert_array_equal(got, np.stack([a, b]))
    a, b = np.triu_indices(4, 1)
    np.testing.assert_array_equal(paddle.triu_indices(4, 4, 1).numpy(),
                                  np.stack([a, b]))
    g = paddle.meshgrid(paddle.to_tensor([1.0, 2.0]),
                        paddle.to_tensor([3.0, 4.0, 5.0]))
    ref = np.meshgrid([1.0, 2.0], [3.0, 4.0, 5.0], indexing="ij")
    np.testing.assert_array_equal(g[0].numpy(), ref[0])
    np.testing.assert_array_equal(g[1].numpy(), ref[1])
    r, th = POS((4,)), T((4,))
    np.testing.assert_allclose(
        paddle.polar(paddle.to_tensor(r), paddle.to_tensor(th)).numpy(),
        r * np.exp(1j * th), rtol=1e-6)
    assert paddle.empty([2, 3]).shape == [2, 3]
    assert paddle.empty_like(x).shape == [2, 3]
    y = paddle.assign(x)
    np.testing.assert_array_equal(y.numpy(), x.numpy())
    np.testing.assert_array_equal(x.clone().numpy(), x.numpy())


def test_shape_and_predicates():
    x = paddle.to_tensor(T((2, 3)))
    np.testing.assert_array_equal(paddle.shape(x).numpy(), [2, 3])
    assert paddle.is_tensor(x) and not paddle.is_tensor(3)
    assert not bool(paddle.is_empty(x))
    assert bool(paddle.is_empty(paddle.to_tensor(
        np.zeros((0, 3), np.float32))))
    # increment
    v = paddle.to_tensor([1.0])
    np.testing.assert_allclose(paddle.increment(v).numpy(), [2.0])


# ---- stochastic creation ops: distribution-moment oracles -----------
def test_random_ops_statistics():
    paddle.seed(123)
    u = paddle.uniform([20000], min=-1, max=3).numpy()
    assert -1 <= u.min() and u.max() < 3 and abs(u.mean() - 1.0) < 0.05
    n = paddle.normal(mean=2.0, std=3.0, shape=[20000]).numpy()
    assert abs(n.mean() - 2.0) < 0.1 and abs(n.std() - 3.0) < 0.1
    g = paddle.standard_normal([20000]).numpy()
    assert abs(g.mean()) < 0.05 and abs(g.std() - 1.0) < 0.05
    r = paddle.randint(0, 7, [10000]).numpy()
    assert r.min() >= 0 and r.max() < 7
    rp = paddle.randperm(100).numpy()
    np.testing.assert_array_equal(np.sort(rp), np.arange(100))
    b = paddle.bernoulli(paddle.full([20000], 0.3)).numpy()
    assert abs(b.mean() - 0.3) < 0.03
    p = paddle.poisson(paddle.full([20000], 4.0)).numpy()
    assert abs(p.mean() - 4.0) < 0.15
    m = paddle.multinomial(paddle.to_tensor(
        [0.1, 0.0, 0.9]), num_samples=5000, replacement=True).numpy()
    assert (m == 1).sum() == 0 and abs((m == 2).mean() - 0.9) < 0.05
    assert paddle.rand([3, 4]).shape == [3, 4]
    x = paddle.to_tensor(T((3, 4)))
    assert paddle.rand_like(x).shape == [3, 4]
    assert paddle.randn_like(x).shape == [3, 4]
    assert paddle.randint_like(x, 0, 5).shape == [3, 4]
    la = paddle.laplace(paddle.full([20000], 1.0),
                        paddle.full([20000], 2.0)).numpy() \
        if hasattr(paddle, "laplace") else None
    gs = paddle.standard_gamma(paddle.full([20000], 3.0)).numpy()
    assert abs(gs.mean() - 3.0) < 0.15
