"""Fourth op-oracle sweep tranche: linalg, search/sort/unique, view and
indexing machinery, misc nn functionals, sequence/decode ops, and alias
schemas — numpy/scipy/torch oracles (VERDICT r1 item 5)."""
import numpy as np
import pytest
import scipy.special as sps
import torch
import torch.nn.functional as tF

import paddle_tpu as paddle
import paddle_tpu._C_ops as C
import paddle_tpu.nn.functional as F
from op_test import OpTest

rng = np.random.RandomState(13)


def T(shape, dtype=np.float32, lo=-2.0, hi=2.0):
    return rng.uniform(lo, hi, shape).astype(dtype)


def POS(shape, dtype=np.float32):
    return rng.uniform(0.2, 3.0, shape).astype(dtype)


def I(shape, hi=5, dtype=np.int32):
    return rng.randint(0, hi, shape).astype(dtype)


def SPD(n):
    a = rng.randn(n, n).astype(np.float32)
    return a @ a.T + n * np.eye(n, dtype=np.float32)


def _t(fn):
    def ref(*arrays, **kw):
        ts = [torch.tensor(a) for a in arrays]
        out = fn(*ts, **kw)
        return out.numpy() if isinstance(out, torch.Tensor) else \
            [o.numpy() for o in out]
    return ref


CASES = [
    # ---- linalg
    ("mm", paddle.mm, np.matmul, {"x": T((3, 4)), "y": T((4, 2))}, {},
     True),
    ("addmm", paddle.addmm,
     lambda inp, x, y, alpha=1.0, beta=1.0: beta * inp + alpha * (x @ y),
     {"input": T((3, 2)), "x": T((3, 4)), "y": T((4, 2))}, {}, True),
    ("einsum", lambda x, y: paddle.einsum("ij,jk->ik", x, y),
     lambda x, y: np.einsum("ij,jk->ik", x, y),
     {"x": T((3, 4)), "y": T((4, 2))}, {}, True),
    ("multi_dot", lambda a, b, c: paddle.linalg.multi_dot([a, b, c]),
     lambda a, b, c: np.linalg.multi_dot([a, b, c]),
     {"a": T((3, 4)), "b": T((4, 5)), "c": T((5, 2))}, {}, False),
    ("norm", lambda x: paddle.norm(x, p=2),
     lambda x: np.linalg.norm(x.reshape(-1)), {"x": T((3, 4))}, {},
     True),
    ("vector_norm", lambda x: paddle.linalg.vector_norm(x, 3.0),
     lambda x: (np.abs(x) ** 3).sum() ** (1 / 3), {"x": T((8,))}, {},
     False),
    ("matrix_norm", lambda x: paddle.linalg.matrix_norm(x, "fro"),
     lambda x: np.linalg.norm(x, "fro"), {"x": T((3, 4))}, {}, False),
    ("p_norm", lambda x: paddle.norm(x, p=3, axis=1),
     lambda x: (np.abs(x) ** 3).sum(1) ** (1 / 3), {"x": T((3, 4))},
     {}, True),
    ("frobenius_norm", lambda x: paddle.norm(x, p="fro"),
     lambda x: np.linalg.norm(x), {"x": T((3, 4))}, {}, True),
    ("squared_l2_norm", lambda x: (x * x).sum(),
     lambda x: (x * x).sum(), {"x": T((8,))}, {}, True),
    ("clip_by_norm", lambda x: C.clip_by_norm(x, 1.0),
     lambda x: x * min(1.0, 1.0 / np.linalg.norm(x.reshape(-1))),
     {"x": T((3, 4))}, {}, False),
    ("renorm", lambda x: paddle.renorm(x, 2.0, 0, 1.0),
     _t(lambda x: torch.renorm(x, 2.0, 0, 1.0)), {"x": T((3, 4))},
     {}, False),
    ("inverse", paddle.inverse, np.linalg.inv, {"x": SPD(4)}, {},
     False),
    ("cholesky_solve",
     lambda b, l: paddle.linalg.cholesky_solve(b, l, upper=False),
     lambda b, l: np.linalg.solve(l @ l.T, b),
     {"b": T((4, 2)), "l": np.linalg.cholesky(SPD(4))}, {}, False),
    ("cholesky_inverse",
     lambda l: paddle.linalg.cholesky_inverse(l),
     lambda l: np.linalg.inv(l @ l.T),
     {"l": np.linalg.cholesky(SPD(4))}, {}, False),
    ("cdist", paddle.cdist, _t(torch.cdist),
     {"x": T((4, 3)), "y": T((5, 3))}, {}, False),
    ("cov", lambda x: paddle.linalg.cov(x),
     lambda x: np.cov(x), {"x": T((3, 6))}, {}, False),
    ("corrcoef", lambda x: paddle.linalg.corrcoef(x),
     lambda x: np.corrcoef(x), {"x": T((3, 6))}, {}, False),
    ("lstsq", lambda a, b: paddle.linalg.lstsq(a, b)[0],
     lambda a, b: np.linalg.lstsq(a, b, rcond=None)[0],
     {"a": T((6, 3)), "b": T((6, 2))}, {}, False),
    ("gammainc", paddle.gammainc if hasattr(paddle, "gammainc") else
     (lambda x, y: paddle.Tensor.gammainc(x, y)), sps.gammainc,
     {"x": POS((6,)), "y": POS((6,))}, {}, False),
    ("gammaincc", paddle.gammaincc if hasattr(paddle, "gammaincc")
     else (lambda x, y: paddle.Tensor.gammaincc(x, y)), sps.gammaincc,
     {"x": POS((6,)), "y": POS((6,))}, {}, False),
    # ---- search / unique / quantile
    ("nonzero", paddle.nonzero,
     lambda x: np.stack(np.nonzero(x), -1),
     {"x": (T((3, 4)) > 0.5).astype(np.float32)}, {}, False),
    ("quantile", lambda x: paddle.quantile(x, 0.3, axis=0),
     lambda x: np.quantile(x, 0.3, 0).astype(np.float32),
     {"x": T((6, 3))}, {}, False),
    ("nanquantile", lambda x: paddle.nanquantile(x, 0.5, axis=0),
     lambda x: np.nanquantile(x, 0.5, 0).astype(np.float32),
     {"x": np.where(T((6, 3)) > 1.0, np.nan, T((6, 3))
                    ).astype(np.float32)}, {}, False),
    ("nanmedian", lambda x: paddle.nanmedian(x, axis=0),
     lambda x: np.nanmedian(x, 0).astype(np.float32),
     {"x": np.where(T((6, 3)) > 1.0, np.nan, T((6, 3))
                    ).astype(np.float32)}, {}, False),
    # ---- views / indexing machinery
    ("slice", lambda x: paddle.slice(x, [0, 1], [0, 1], [2, 3]),
     lambda x: x[0:2, 1:3], {"x": T((4, 5))}, {}, False),
    ("strided_slice",
     lambda x: paddle.strided_slice(x, [1], [0], [5], [2]),
     lambda x: x[:, 0:5:2], {"x": T((3, 6))}, {}, False),
    ("crop", lambda x: paddle.crop(x, shape=[2, 2], offsets=[1, 1]),
     lambda x: x[1:3, 1:3], {"x": T((4, 5))}, {}, False),
    ("view", lambda x: x.view([3, 2]), lambda x: x.reshape(3, 2),
     {"x": T((2, 3))}, {}, False),
    ("view_as", lambda x, y: x.view_as(y),
     lambda x, y: x.reshape(y.shape),
     {"x": T((2, 3)), "y": T((6,))}, {}, False),
    ("as_strided",
     lambda x: paddle.as_strided(x, [2, 2], [3, 1], 1),
     lambda x: np.lib.stride_tricks.as_strided(
         x.reshape(-1)[1:], (2, 2), (12, 4)).copy(),
     {"x": T((3, 3))}, {}, False),
    ("view_dtype", lambda x: C.view_dtype(x, "int32"),
     lambda x: x.view(np.int32), {"x": T((2, 4))}, {}, False),
    ("tensor_unfold", lambda x: C.tensor_unfold(x, 0, 2, 1),
     lambda x: np.lib.stride_tricks.sliding_window_view(x, 2, 0),
     {"x": T((4, 3))}, {}, False),
    ("split_with_num", lambda x: paddle.split(x, 3, axis=1)[2],
     lambda x: np.split(x, 3, 1)[2], {"x": T((2, 6))}, {}, False),
    ("reverse", lambda x: paddle.flip(x, [0]),
     lambda x: np.flip(x, 0), {"x": T((3, 4))}, {}, False),
    ("fill", lambda x: x.clone().fill_(3.5),
     lambda x: np.full_like(x, 3.5), {"x": T((3, 4))}, {}, False),
    ("index_put",
     lambda x, ix, v: paddle.index_put(x, [ix], v),
     lambda x, ix, v: _np_index_put(x, ix, v),
     {"x": T((5, 3)), "indices": np.array([1, 3], np.int64),
      "value": T((2, 3))}, {}, False),
    ("masked_scatter", paddle.masked_scatter,
     lambda x, m, v: _np_masked_scatter(x, m, v),
     {"x": T((8,)), "mask": T((8,)) > 0, "value": T((8,))}, {},
     False),
    ("scatter_nd_add", paddle.scatter_nd_add,
     lambda x, idx, u: _np_scatter_nd_add(x, idx, u),
     {"x": T((6,)), "index": I((4, 1), 6).astype(np.int64),
      "updates": T((4,))}, {}, False),
    ("scatter_nd", lambda idx, u: paddle.scatter_nd(idx, u, [6]),
     lambda idx, u: _np_scatter_nd_add(np.zeros(6, np.float32), idx,
                                       u),
     {"index": I((4, 1), 6).astype(np.int64), "updates": T((4,))},
     {}, False),
    ("shard_index",
     lambda x: paddle.shard_index(x, 20, 2, 1, -1),
     lambda x: np.where((x // 10) == 1, x % 10, -1),
     {"x": I((6, 1), 20).astype(np.int64)}, {}, False),
    ("reduce_as", lambda x, y: paddle.reduce_as(x, y),
     lambda x, y: x.sum(0, keepdims=False),
     {"x": T((4, 3)), "target": T((3,))}, {}, False),
    # ---- misc nn functionals
    ("linear", F.linear, lambda x, w, b: x @ w + b,
     {"x": T((4, 3)), "weight": T((3, 5)), "bias": T((5,))}, {},
     True),
    ("embedding",
     lambda ids, w: F.embedding(ids, w),
     lambda ids, w: w[ids],
     {"x": I((5,), 7).astype(np.int64), "weight": T((7, 4))}, {},
     False),
    ("cosine_similarity", F.cosine_similarity,
     _t(tF.cosine_similarity), {"x1": T((4, 6)), "x2": T((4, 6))},
     {}, True),
    ("normalize", lambda x: F.normalize(x, axis=-1),
     lambda x: x / np.linalg.norm(x, axis=-1, keepdims=True).clip(
         1e-12), {"x": T((4, 6))}, {}, True),
    ("label_smooth",
     lambda x: F.label_smooth(x, epsilon=0.1),
     lambda x: x * 0.9 + 0.1 / x.shape[-1], {"x": T((4, 5), lo=0,
                                                   hi=1)}, {}, False),
    ("bilinear", F.bilinear, _t(tF.bilinear),
     {"x1": T((4, 3)), "x2": T((4, 5)), "weight": T((6, 3, 5)),
      "bias": T((6,))}, {}, False),
    ("pixel_shuffle", lambda x: F.pixel_shuffle(x, 2),
     _t(lambda x: tF.pixel_shuffle(x, 2)), {"x": T((2, 8, 3, 3))},
     {}, False),
    ("pixel_unshuffle", lambda x: F.pixel_unshuffle(x, 2),
     _t(lambda x: tF.pixel_unshuffle(x, 2)), {"x": T((2, 2, 4, 4))},
     {}, False),
    ("channel_shuffle", lambda x: F.channel_shuffle(x, 2),
     _t(lambda x: tF.channel_shuffle(x, 2)), {"x": T((2, 4, 3, 3))},
     {}, False),
    ("local_response_norm",
     lambda x: F.local_response_norm(x, size=3),
     lambda x: _np_lrn(x, 3),     # paddle semantics: alpha NOT /size
     {"x": T((2, 6, 4, 4))}, {}, False),
    ("rms_norm_incubate",
     lambda x, w: paddle.incubate.nn.functional.fused_rms_norm(
         x, w, None, 1e-6, 1)[0],
     lambda x, w: x / np.sqrt((x * x).mean(-1, keepdims=True)
                              + 1e-6) * w,
     {"x": T((4, 6)), "weight": POS((6,))}, {}, False),
    ("fold", lambda x: F.fold(x, [4, 4], [2, 2], strides=2),
     _t(lambda x: tF.fold(x, (4, 4), (2, 2), stride=2)),
     {"x": T((2, 12, 4))}, {}, False),
    ("sequence_mask",
     lambda x: paddle.nn.functional.sequence_mask(x, maxlen=6),
     lambda x: (np.arange(6)[None] < x[:, None]),
     {"x": np.array([2, 5, 3], np.int64)}, {}, False),
    ("temporal_shift", lambda x: F.temporal_shift(x, 2, 0.25),
     lambda x: _np_temporal_shift(x, 2, 0.25),
     {"x": T((4, 8, 3, 3))}, {}, False),
    ("pad3d", lambda x: F.pad(x, [1, 1, 1, 1, 1, 1], value=0.0,
                              data_format="NCDHW"),
     _t(lambda x: tF.pad(x, (1, 1, 1, 1, 1, 1))),
     {"x": T((1, 2, 3, 3, 3))}, {}, False),
    ("affine_grid",
     lambda theta: F.affine_grid(theta, [2, 1, 4, 4],
                                 align_corners=False),
     _t(lambda th: tF.affine_grid(th, (2, 1, 4, 4),
                                  align_corners=False)),
     {"theta": T((2, 2, 3))}, {}, False),
    ("grid_sample",
     lambda x, g: F.grid_sample(x, g, align_corners=False),
     _t(lambda x, g: tF.grid_sample(x, g, align_corners=False)),
     {"x": T((2, 2, 4, 4)), "grid": T((2, 3, 3, 2), lo=-1, hi=1)},
     {}, False),
    ("flash_attn",
     lambda q, k, v: F.scaled_dot_product_attention(
         q, k, v, is_causal=False),
     lambda q, k, v: _np_attention(q, k, v),
     {"q": T((2, 5, 2, 4)), "k": T((2, 5, 2, 4)),
      "v": T((2, 5, 2, 4))}, {}, False),
    ("fused_softmax_mask",
     lambda x, m: paddle.incubate.softmax_mask_fuse(x, m)
     if hasattr(paddle.incubate, "softmax_mask_fuse") else
     F.softmax(x + m, axis=-1),
     lambda x, m: sps.softmax(x + m, -1),
     {"x": T((2, 2, 4, 4)), "mask": (I((2, 1, 4, 4), 2) * -1e9
                                     ).astype(np.float32)}, {},
     False),
    ("fused_softmax_mask_upper_triangle",
     lambda x: paddle.incubate.softmax_mask_fuse_upper_triangle(x)
     if hasattr(paddle.incubate, "softmax_mask_fuse_upper_triangle")
     else F.softmax(x + np.triu(np.full((4, 4), -1e9, np.float32), 1),
                    axis=-1),
     lambda x: sps.softmax(
         x + np.triu(np.full((4, 4), -1e9, np.float32), 1), -1),
     {"x": T((2, 2, 4, 4))}, {}, False),
    # ---- losses not in tranche 3
    ("cosine_embedding_loss", F.cosine_embedding_loss,
     _t(tF.cosine_embedding_loss),
     {"input1": T((4, 6)), "input2": T((4, 6)),
      "label": (I((4,), 2) * 2 - 1).astype(np.float32)}, {}, False),
    ("hinge_embedding_loss", F.hinge_embedding_loss,
     _t(tF.hinge_embedding_loss),
     {"input": T((4, 3)),
      "label": (I((4, 3), 2) * 2 - 1).astype(np.float32)}, {},
     False),
    ("triplet_margin_loss", F.triplet_margin_loss,
     _t(tF.triplet_margin_loss),
     {"input": T((4, 6)), "positive": T((4, 6)),
      "negative": T((4, 6))}, {}, False),
    ("multi_label_soft_margin_loss", F.multi_label_soft_margin_loss,
     _t(tF.multilabel_soft_margin_loss),
     {"input": T((4, 3)), "label": I((4, 3), 2).astype(np.float32)},
     {}, False),
    ("softmax_with_cross_entropy",
     lambda x, l: F.softmax_with_cross_entropy(x, l),
     lambda x, l: -np.log(sps.softmax(x, -1))[
         np.arange(4), l[:, 0]][:, None],
     {"logits": T((4, 5)), "label": I((4, 1), 5).astype(np.int64)},
     {}, False),
    ("npair_loss", F.npair_loss,
     lambda a, p, l: _np_npair(a, p, l),
     {"anchor": T((4, 6)) * 0.3, "positive": T((4, 6)) * 0.3,
      "labels": I((4,), 3).astype(np.int64)}, {}, False),
    # ---- sequence / decode
    ("edit_distance", lambda h, r: C.edit_distance(h, r),
     lambda h, r: np.array([_levenshtein(h[0], r[0]),
                            _levenshtein(h[1], r[1])], np.float32),
     {"hyp": I((2, 5), 8).astype(np.int64),
      "ref": I((2, 5), 8).astype(np.int64)}, {}, False),
    ("segment_pool",
     lambda x, ids: paddle.geometric.segment_sum(x, ids),
     lambda x, ids: np.stack([x[ids == i].sum(0) for i in
                              range(int(ids.max()) + 1)]),
     {"x": T((6, 3)), "ids": np.array([0, 0, 1, 1, 1, 2],
                                      np.int64)}, {}, False),
    ("send_u_recv",
     lambda x, si, di: paddle.geometric.send_u_recv(
         x, si, di, reduce_op="sum"),
     lambda x, si, di: _np_send_u_recv(x, si, di),
     {"x": T((4, 3)), "src_index": np.array([0, 1, 2, 0], np.int64),
      "dst_index": np.array([1, 2, 1, 3], np.int64)}, {}, False),
]


def _np_index_put(x, ix, v):
    out = x.copy()
    out[ix] = v
    return out


def _np_masked_scatter(x, m, v):
    out = x.copy()
    out[m] = v[: m.sum()]
    return out


def _np_scatter_nd_add(x, idx, u):
    out = x.copy()
    np.add.at(out, idx[:, 0], u)
    return out


def _np_temporal_shift(x, seg, ratio):
    nt, c, h, w = x.shape
    n, t = nt // seg, seg
    y = x.reshape(n, t, c, h, w)
    fold = int(c * ratio)
    out = np.zeros_like(y)
    out[:, :-1, :fold] = y[:, 1:, :fold]                  # shift left
    out[:, 1:, fold:2 * fold] = y[:, :-1, fold:2 * fold]  # shift right
    out[:, :, 2 * fold:] = y[:, :, 2 * fold:]
    return out.reshape(nt, c, h, w)


def _np_attention(q, k, v):
    # [B, S, H, D] layout
    d = q.shape[-1]
    logits = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    p = sps.softmax(logits, -1)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


def _np_npair(a, p, l, l2_reg=0.002):
    sim = a @ p.T
    tgt = (l[:, None] == l[None, :]).astype(np.float32)
    tgt /= tgt.sum(1, keepdims=True)
    ce = -( tgt * np.log(sps.softmax(sim, -1))).sum(1).mean()
    reg = l2_reg * ((a * a).sum(1).mean()
                    + (p * p).sum(1).mean()) * 0.25
    return ce + reg


def _np_lrn(x, size, alpha=1e-4, beta=0.75, k=1.0):
    sq = np.square(x)
    n, c = x.shape[:2]
    acc = np.zeros_like(x)
    for i in range(c):
        lo, hi = max(0, i - size // 2), min(c, i + (size - 1) // 2 + 1)
        acc[:, i] = sq[:, lo:hi].sum(1)
    return x / (k + alpha * acc) ** beta


def _np_send_u_recv(x, si, di):
    out = np.zeros((int(di.max()) + 1,) + x.shape[1:], x.dtype)
    np.add.at(out, di, x[si])
    return out


def _levenshtein(a, b):
    la, lb = len(a), len(b)
    d = np.arange(lb + 1, dtype=np.int64)
    for i in range(1, la + 1):
        prev = d.copy()
        d[0] = i
        for j in range(1, lb + 1):
            d[j] = min(prev[j] + 1, d[j - 1] + 1,
                       prev[j - 1] + (a[i - 1] != b[j - 1]))
    return float(d[lb])


@pytest.mark.parametrize(
    "name,op,ref,inputs,attrs,grad", CASES,
    ids=[c[0] for c in CASES])
def test_op_oracle(name, op, ref, inputs, attrs, grad):
    class Case(OpTest):
        rtol = 1e-4
        atol = 1e-5

    Case.op = staticmethod(op)
    Case.ref = staticmethod(ref)
    Case.inputs = inputs
    Case.attrs = attrs
    t = Case()
    t.check_output()
    if grad:
        t.check_grad()


# ---- decompositions: compare via reconstruction / invariants --------
def test_factorizations_reconstruct():
    a = T((5, 3))
    q, r = paddle.linalg.qr(paddle.to_tensor(a))
    np.testing.assert_allclose(q.numpy() @ r.numpy(), a, atol=1e-5)
    u, s, vh = paddle.linalg.svd(paddle.to_tensor(a),
                                 full_matrices=False)
    np.testing.assert_allclose(
        u.numpy() @ np.diag(s.numpy()) @ vh.numpy(), a, atol=1e-5)
    spd = SPD(4)
    w, v = paddle.linalg.eigh(paddle.to_tensor(spd))
    np.testing.assert_allclose(
        v.numpy() @ np.diag(w.numpy()) @ v.numpy().T, spd, atol=1e-4)
    sq = T((4, 4))
    ev = paddle.linalg.eigvals(paddle.to_tensor(sq)).numpy()
    ref = np.linalg.eigvals(sq)
    np.testing.assert_allclose(np.sort_complex(ev),
                               np.sort_complex(ref), atol=1e-4)
    w2, v2 = paddle.linalg.eig(paddle.to_tensor(sq))
    np.testing.assert_allclose(
        sq.astype(np.complex64) @ v2.numpy(),
        v2.numpy() * w2.numpy()[None, :], atol=1e-4)
    lu, piv = paddle.linalg.lu(paddle.to_tensor(a))
    p, l, u_ = paddle.linalg.lu_unpack(lu, piv)
    np.testing.assert_allclose(
        p.numpy() @ l.numpy() @ u_.numpy(), a, atol=1e-5)
    # householder_product / ormqr via the geqrf-style inputs
    if hasattr(paddle.linalg, "householder_product"):
        hq = paddle.linalg.householder_product
        tq, tau = np.linalg.qr(a)[0], None
    # svd_lowrank reconstructs approximately for a low-rank matrix
    lr = T((6, 2)) @ T((2, 5))
    u3, s3, v3 = paddle.linalg.svd_lowrank(paddle.to_tensor(lr), q=2)
    np.testing.assert_allclose(
        u3.numpy() @ np.diag(s3.numpy()) @ v3.numpy().T, lr,
        atol=1e-4)


def test_unique_and_histogram():
    x = np.array([3, 1, 2, 3, 1, 7], np.int64)
    got = paddle.unique(paddle.to_tensor(x)).numpy()
    np.testing.assert_array_equal(got, np.unique(x))
    xc = np.array([1, 1, 2, 2, 2, 1], np.int64)
    got = paddle.unique_consecutive(paddle.to_tensor(xc)).numpy()
    np.testing.assert_array_equal(got, [1, 2, 1])
    pts = T((20, 2))
    h_ref, edges = np.histogramdd(pts.astype(np.float64),
                                  bins=(3, 3))
    h, _ = paddle.histogramdd(paddle.to_tensor(pts), bins=[3, 3])
    np.testing.assert_allclose(h.numpy(), h_ref)


def test_decode_ops():
    # viterbi_decode vs a tiny numpy DP
    emis = T((1, 3, 4))
    trans = T((4, 4))
    lens = np.array([3], np.int64)
    scores, path = paddle.text.viterbi_decode(
        paddle.to_tensor(emis), paddle.to_tensor(trans),
        paddle.to_tensor(lens), include_bos_eos_tag=False)
    dp = emis[0, 0]
    back = []
    for t in range(1, 3):
        m = dp[:, None] + trans
        back.append(m.argmax(0))
        dp = m.max(0) + emis[0, t]
    best_last = int(dp.argmax())
    ref_path = [best_last]
    for b in reversed(back):
        ref_path.append(int(b[ref_path[-1]]))
    ref_path.reverse()
    np.testing.assert_allclose(float(scores.numpy()[0]), dp.max(),
                               rtol=1e-5)
    np.testing.assert_array_equal(path.numpy()[0], ref_path)
    # gather_tree (beam search backtrace)
    ids = I((3, 1, 2), 5).astype(np.int64)      # [T, B, beam]
    parents = np.zeros_like(ids)
    out = paddle.nn.functional.gather_tree(
        paddle.to_tensor(ids), paddle.to_tensor(parents)).numpy()
    # with parent 0 everywhere, beam k at t<T-1 follows parent chain 0
    ref = ids.copy()
    for t in range(1, 3):
        ref[2 - t] = ids[2 - t][:, parents[3 - t][:, 0]]
    assert out.shape == ids.shape
    # top_p_sampling: peaked distribution must return the peak
    probs = np.full((2, 10), 0.001, np.float32)
    probs[:, 4] = 0.991
    probs /= probs.sum(-1, keepdims=True)
    ids_out = paddle.tensor.top_p_sampling(
        paddle.to_tensor(probs), paddle.to_tensor(
            np.full((2, 1), 0.5, np.float32)))[1].numpy()
    assert (ids_out == 4).all()


def test_dropout_family():
    x = paddle.to_tensor(T((64, 64)), stop_gradient=False)
    # p=0: identity; p=1: zeros (train mode)
    np.testing.assert_array_equal(F.dropout(x, p=0.0).numpy(),
                                  x.numpy())
    assert np.all(F.dropout(x, p=1.0).numpy() == 0)
    # eval mode: identity regardless of p
    np.testing.assert_array_equal(
        F.dropout(x, p=0.7, training=False).numpy(), x.numpy())
    # train mode keeps ~ (1-p) fraction, scaled to preserve mean
    paddle.seed(5)
    y = F.dropout(x, p=0.5).numpy()
    keep = (y != 0).mean()
    assert abs(keep - 0.5) < 0.06
    np.testing.assert_allclose(y[y != 0],
                               x.numpy()[y != 0] / 0.5, rtol=1e-6)
    for fn, shape in ((F.dropout2d, (2, 3, 4, 4)),
                      (F.dropout3d, (2, 3, 2, 4, 4))):
        z = paddle.to_tensor(T(shape))
        np.testing.assert_array_equal(fn(z, p=0.0).numpy(), z.numpy())
    z = paddle.to_tensor(T((32, 32)))
    np.testing.assert_array_equal(F.alpha_dropout(z, p=0.0).numpy(),
                                  z.numpy())
    # rrelu eval mode == leaky with mean slope
    r = F.rrelu(x, lower=0.2, upper=0.4, training=False).numpy()
    np.testing.assert_allclose(
        r, np.where(x.numpy() >= 0, x.numpy(), 0.3 * x.numpy()),
        rtol=1e-6)
    # gumbel_softmax: rows sum to 1; hard=True one-hot argmax property
    g = F.gumbel_softmax(paddle.to_tensor(T((8, 5))), hard=True)
    np.testing.assert_allclose(g.numpy().sum(-1), np.ones(8),
                               rtol=1e-5)
    assert ((g.numpy() == 1).sum(-1) == 1).all()


def test_alias_schemas():
    """Schemas that are exact aliases of swept ops — pinned to the
    same numerics so the alias cannot drift."""
    x, y = T((6,)), POS((6,))
    tx, ty = paddle.to_tensor(x), paddle.to_tensor(y)
    np.testing.assert_allclose(paddle.floor_mod(tx, ty).numpy(),
                               np.mod(x, y), rtol=1e-6)
    np.testing.assert_allclose(F.log_sigmoid(tx).numpy(),
                               -np.log1p(np.exp(-x)), rtol=1e-5)
    np.testing.assert_allclose(F.tanhshrink(tx).numpy(),
                               x - np.tanh(x), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(F.swish(tx).numpy(),
                               x / (1 + np.exp(-x)), rtol=1e-5)
    lbl = paddle.to_tensor((y > 1).astype(np.float32))
    np.testing.assert_allclose(
        F.binary_cross_entropy(F.sigmoid(tx), lbl).numpy(),
        tF.binary_cross_entropy(torch.sigmoid(torch.tensor(x)),
                                torch.tensor((y > 1).astype(
                                    np.float32))).numpy(), rtol=1e-5)


def test_stochastic_value_ops():
    paddle.seed(77)
    b = paddle.binomial(paddle.full([20000], 10.0),
                        paddle.full([20000], 0.3)).numpy()
    assert abs(b.mean() - 3.0) < 0.1
    from paddle_tpu.ops.extra import dirichlet
    d = dirichlet(paddle.full([5000, 3], 2.0)).numpy()
    np.testing.assert_allclose(d.sum(-1), np.ones(5000), rtol=1e-5)
    assert abs(d.mean() - 1 / 3) < 0.02
    from paddle_tpu.ops.random import gaussian
    assert gaussian([4, 4]).shape == [4, 4]
