"""Fifth op-oracle sweep tranche: conv/pool dimensional variants,
interpolate modes, signal ops (stft/frame/overlap_add), remaining
linalg (householder_product/ormqr), ctc, and alias schemas."""
import numpy as np
import pytest
import scipy.signal
import torch
import torch.nn.functional as tF

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

rng = np.random.RandomState(17)


def T(shape, lo=-2.0, hi=2.0):
    return rng.uniform(lo, hi, shape).astype(np.float32)


def _cmp(got, ref, rtol=1e-4, atol=1e-5):
    np.testing.assert_allclose(np.asarray(got.numpy()), ref,
                               rtol=rtol, atol=atol)


def test_conv_transpose_variants():
    x1 = T((2, 3, 8))
    w1 = T((3, 4, 3)) * 0.2
    _cmp(F.conv1d_transpose(paddle.to_tensor(x1), paddle.to_tensor(w1),
                            stride=2, padding=1),
         tF.conv_transpose1d(torch.tensor(x1), torch.tensor(w1),
                             stride=2, padding=1).numpy())
    x3 = T((1, 2, 4, 4, 4))
    w3 = T((2, 3, 3, 3, 3)) * 0.2
    _cmp(F.conv3d_transpose(paddle.to_tensor(x3), paddle.to_tensor(w3),
                            stride=2),
         tF.conv_transpose3d(torch.tensor(x3), torch.tensor(w3),
                             stride=2).numpy())


def test_pool_dimensional_variants():
    x1 = T((2, 3, 10))
    _cmp(F.avg_pool1d(paddle.to_tensor(x1), 2),
         tF.avg_pool1d(torch.tensor(x1), 2).numpy())
    _cmp(F.max_pool1d(paddle.to_tensor(x1), 2),
         tF.max_pool1d(torch.tensor(x1), 2).numpy())
    _cmp(F.adaptive_avg_pool1d(paddle.to_tensor(x1), 5),
         tF.adaptive_avg_pool1d(torch.tensor(x1), 5).numpy())
    _cmp(F.adaptive_max_pool1d(paddle.to_tensor(x1), 5),
         tF.adaptive_max_pool1d(torch.tensor(x1), 5).numpy())
    x3 = T((1, 2, 6, 6, 6))
    _cmp(F.avg_pool3d(paddle.to_tensor(x3), 2),
         tF.avg_pool3d(torch.tensor(x3), 2).numpy())
    _cmp(F.max_pool3d(paddle.to_tensor(x3), 2),
         tF.max_pool3d(torch.tensor(x3), 2).numpy())
    _cmp(F.adaptive_avg_pool3d(paddle.to_tensor(x3), 3),
         tF.adaptive_avg_pool3d(torch.tensor(x3), 3).numpy())
    _cmp(F.adaptive_max_pool3d(paddle.to_tensor(x3), 3),
         tF.adaptive_max_pool3d(torch.tensor(x3), 3).numpy())
    _cmp(F.lp_pool1d(paddle.to_tensor(x1), 2.0, 2),
         tF.lp_pool1d(torch.tensor(x1), 2.0, 2).numpy())
    x2 = T((2, 3, 6, 6))
    _cmp(F.lp_pool2d(paddle.to_tensor(x2), 2.0, 2),
         tF.lp_pool2d(torch.tensor(x2), 2.0, 2).numpy())


def test_max_pool_with_index_and_unpool():
    x = T((1, 2, 6, 6))
    out, idx = F.max_pool2d(paddle.to_tensor(x), 2,
                            return_mask=True)
    t_out, t_idx = tF.max_pool2d(torch.tensor(x), 2,
                                 return_indices=True)
    _cmp(out, t_out.numpy())
    np.testing.assert_array_equal(idx.numpy(), t_idx.numpy())
    un = F.max_unpool2d(out, idx, 2)
    t_un = tF.max_unpool2d(t_out, t_idx, 2)
    _cmp(un, t_un.numpy())
    # the pooled VALUES stay differentiable with return_mask=True
    xg = paddle.to_tensor(x, stop_gradient=False)
    out_g, _ = F.max_pool2d(xg, 2, return_mask=True)
    out_g.sum().backward()
    xt = torch.tensor(x, requires_grad=True)
    t_o, _ = tF.max_pool2d(xt, 2, return_indices=True)
    t_o.sum().backward()
    np.testing.assert_allclose(xg.grad.numpy(), xt.grad.numpy())


def test_interpolate_modes_cover_interp_schemas():
    """F.interpolate modes are the public surface of the
    {bilinear,nearest,bicubic,linear,trilinear}_interp kernels."""
    x2 = T((1, 2, 5, 5))
    for mode in ("nearest", "bilinear", "bicubic"):
        kw = {} if mode == "nearest" else {"align_corners": False}
        _cmp(F.interpolate(paddle.to_tensor(x2), size=[8, 8],
                           mode=mode, **kw),
             tF.interpolate(torch.tensor(x2), size=(8, 8), mode=mode,
                            **kw).numpy(), rtol=1e-3, atol=1e-4)
    x1 = T((1, 2, 6))
    _cmp(F.interpolate(paddle.to_tensor(x1), size=[9], mode="linear",
                       align_corners=False),
         tF.interpolate(torch.tensor(x1), size=9, mode="linear",
                        align_corners=False).numpy(), rtol=1e-4)
    x3 = T((1, 1, 4, 4, 4))
    _cmp(F.interpolate(paddle.to_tensor(x3), size=[6, 6, 6],
                       mode="trilinear", align_corners=False),
         tF.interpolate(torch.tensor(x3), size=(6, 6, 6),
                        mode="trilinear",
                        align_corners=False).numpy(), rtol=1e-4)
    # upsample is the same kernel family
    _cmp(F.upsample(paddle.to_tensor(x2), scale_factor=2,
                    mode="nearest"),
         tF.interpolate(torch.tensor(x2), scale_factor=2,
                        mode="nearest").numpy())


def test_norm_layers_direct():
    x = T((4, 6))
    g, b = T((6,)), T((6,))
    _cmp(F.layer_norm(paddle.to_tensor(x), normalized_shape=[6],
                      weight=paddle.to_tensor(g),
                      bias=paddle.to_tensor(b)),
         tF.layer_norm(torch.tensor(x), [6], torch.tensor(g),
                       torch.tensor(b)).numpy(), rtol=1e-4)
    # rms_norm schema == incubate fused_rms_norm capability
    w = T((6,), 0.5, 1.5)
    got = paddle.incubate.nn.functional.fused_rms_norm(
        paddle.to_tensor(x), paddle.to_tensor(w), None, 1e-6, 1)[0]
    ref = x / np.sqrt((x * x).mean(-1, keepdims=True) + 1e-6) * w
    _cmp(got, ref)


def test_ctc_loss_vs_torch():
    tdim, b, c = 6, 2, 5
    logits = T((tdim, b, c))
    labels = rng.randint(1, c, (b, 3)).astype(np.int32)
    in_len = np.full((b,), tdim, np.int64)
    lbl_len = np.full((b,), 3, np.int64)
    got = F.ctc_loss(paddle.to_tensor(logits),
                     paddle.to_tensor(labels),
                     paddle.to_tensor(in_len),
                     paddle.to_tensor(lbl_len),
                     blank=0, reduction="none")
    ref = tF.ctc_loss(torch.tensor(logits).log_softmax(-1),
                      torch.tensor(labels.astype(np.int64)),
                      torch.tensor(in_len), torch.tensor(lbl_len),
                      blank=0, reduction="none")
    np.testing.assert_allclose(got.numpy().reshape(-1), ref.numpy(),
                               rtol=1e-4, atol=1e-4)


def test_margin_cross_entropy():
    # cosine-margin loss: inputs are cosines, domain [-1, 1]
    logits = T((4, 6), lo=-0.9, hi=0.9)
    label = rng.randint(0, 6, (4,)).astype(np.int64)
    loss, softmax = F.margin_cross_entropy(
        paddle.to_tensor(logits), paddle.to_tensor(label),
        margin1=1.0, margin2=0.0, margin3=0.0, scale=1.0,
        return_softmax=True)
    # with neutral margins this is plain softmax CE
    import scipy.special as sps
    p = sps.softmax(logits, -1)
    ref = -np.log(p[np.arange(4), label])
    np.testing.assert_allclose(loss.numpy().reshape(-1), ref,
                               rtol=1e-4)


def test_signal_ops_vs_scipy():
    x = T((2, 64))
    fr = paddle.signal.frame(paddle.to_tensor(x), frame_length=16,
                             hop_length=8)
    # reference layout: frames stacked on a new trailing axis
    ref = np.stack([x[:, i * 8: i * 8 + 16]
                    for i in range(7)], -1)
    np.testing.assert_allclose(fr.numpy(), ref, rtol=1e-6)
    back = paddle.signal.overlap_add(fr, hop_length=8)
    win = np.zeros(64, np.float32)
    acc = np.zeros((2, 64), np.float32)
    for i in range(7):
        acc[:, i * 8: i * 8 + 16] += ref[..., i]
    np.testing.assert_allclose(back.numpy(), acc, rtol=1e-5)
    # stft vs scipy
    st = paddle.signal.stft(paddle.to_tensor(x), n_fft=16,
                            hop_length=8, center=False,
                            onesided=True).numpy()
    f_, t_, ref_st = scipy.signal.stft(
        x, nperseg=16, noverlap=8, window=np.ones(16), padded=False,
        boundary=None, return_onesided=True)
    np.testing.assert_allclose(st, ref_st * 16, rtol=1e-4, atol=1e-4)


def test_householder_product_and_ormqr():
    a = T((5, 3))
    # LAPACK geqrf reflectors + taus via numpy's raw mode
    geqrf, tau = np.linalg.qr(a, mode="raw")
    q_ref = np.linalg.qr(a)[0]
    got_q = paddle.linalg.householder_product(
        paddle.to_tensor(geqrf.T.astype(np.float32).copy()),
        paddle.to_tensor(tau.astype(np.float32))).numpy()
    # Q is unique up to column signs given the reflectors — compare
    # reconstruction instead: Q from reflectors must be orthonormal
    # and span the same subspace
    np.testing.assert_allclose(got_q.T @ got_q, np.eye(3), atol=1e-4)
    np.testing.assert_allclose(np.abs(q_ref.T @ got_q),
                               np.eye(3), atol=1e-4)
    if hasattr(paddle.linalg, "ormqr"):
        # ormqr applies the (full, implicit) orthogonal Q: it must
        # preserve norms, and Q^T(Qc) must round-trip to c
        c = T((5, 2))
        refl = paddle.to_tensor(geqrf.T.astype(np.float32).copy())
        taut = paddle.to_tensor(tau.astype(np.float32))
        z = paddle.linalg.ormqr(refl, taut, paddle.to_tensor(c))
        np.testing.assert_allclose(
            np.linalg.norm(z.numpy(), axis=0),
            np.linalg.norm(c, axis=0), rtol=1e-4)
        back = paddle.linalg.ormqr(refl, taut, z,
                                   transpose=True).numpy()
        np.testing.assert_allclose(back, c, atol=1e-4)


def test_alias_loss_schemas():
    """bce_loss / kldiv_loss / hinge_loss /
    sigmoid_cross_entropy_with_logits are kernel-level aliases of the
    swept public losses — pin them to the same numerics."""
    x = rng.uniform(0.05, 0.95, (4, 3)).astype(np.float32)
    y = rng.randint(0, 2, (4, 3)).astype(np.float32)
    _cmp(F.binary_cross_entropy(paddle.to_tensor(x),
                                paddle.to_tensor(y)),
         tF.binary_cross_entropy(torch.tensor(x),
                                 torch.tensor(y)).numpy())
    logit = T((4, 3))
    _cmp(F.binary_cross_entropy_with_logits(paddle.to_tensor(logit),
                                            paddle.to_tensor(y)),
         tF.binary_cross_entropy_with_logits(
             torch.tensor(logit), torch.tensor(y)).numpy())
    logp = np.log(rng.uniform(0.1, 0.9, (4, 3))).astype(np.float32)
    tgt = rng.uniform(0.1, 0.9, (4, 3)).astype(np.float32)
    _cmp(F.kl_div(paddle.to_tensor(logp), paddle.to_tensor(tgt),
                  reduction="batchmean"),
         tF.kl_div(torch.tensor(logp), torch.tensor(tgt),
                   reduction="batchmean").numpy())
    lbl = (rng.randint(0, 2, (6,)) * 2 - 1).astype(np.float32)
    inp = T((6,))
    _cmp(F.hinge_embedding_loss(paddle.to_tensor(inp),
                                paddle.to_tensor(lbl)),
         tF.hinge_embedding_loss(torch.tensor(inp),
                                 torch.tensor(lbl)).numpy())


def test_unfold_im2col():
    x = T((2, 3, 6, 6))
    got = F.unfold(paddle.to_tensor(x), kernel_sizes=2, strides=2)
    ref = tF.unfold(torch.tensor(x), 2, stride=2).numpy()
    np.testing.assert_allclose(got.numpy(), ref, rtol=1e-5)


def test_view_shape_alias():
    x = paddle.to_tensor(T((2, 6)))
    np.testing.assert_array_equal(x.view([3, 4]).numpy(),
                                  x.numpy().reshape(3, 4))
    np.testing.assert_array_equal(
        paddle.reshape(x, [4, 3]).numpy(), x.numpy().reshape(4, 3))


def test_shuffle_channel_alias():
    x = T((2, 4, 3, 3))
    got = F.channel_shuffle(paddle.to_tensor(x), 2)
    ref = tF.channel_shuffle(torch.tensor(x), 2).numpy()
    np.testing.assert_allclose(got.numpy(), ref)
