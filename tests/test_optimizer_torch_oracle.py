"""Optimizer update-rule numerics vs torch with identical params/grads
(reference mechanism: per-op adam/sgd/momentum op tests vs numpy)."""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
from paddle_tpu import nn

rs = np.random.RandomState(13)


def _pair(lr_kwargs_ours, torch_cls, torch_kwargs, ours_cls, steps=4):
    w0 = rs.randn(4, 3).astype(np.float32)
    grads = [rs.randn(4, 3).astype(np.float32) for _ in range(steps)]

    lin = nn.Linear(4, 3, bias_attr=False)
    lin.weight._assign_array(paddle.to_tensor(w0)._data)
    opt = ours_cls(parameters=lin.parameters(), **lr_kwargs_ours)

    tw = torch.tensor(w0.T.copy(), requires_grad=True)  # torch [out,in]
    topt = torch_cls([tw], **torch_kwargs)

    for g in grads:
        lin.weight.clear_grad()
        lin.weight.grad = paddle.to_tensor(g)
        opt.step()
        topt.zero_grad()
        tw.grad = torch.tensor(g.T.copy())
        topt.step()
    np.testing.assert_allclose(lin.weight.numpy(),
                               tw.detach().numpy().T, rtol=2e-5,
                               atol=2e-6)


def test_sgd_matches_torch():
    _pair(dict(learning_rate=0.1), torch.optim.SGD, dict(lr=0.1),
          paddle.optimizer.SGD)


def test_momentum_matches_torch():
    _pair(dict(learning_rate=0.05, momentum=0.9),
          torch.optim.SGD, dict(lr=0.05, momentum=0.9),
          paddle.optimizer.Momentum)


def test_adam_matches_torch():
    _pair(dict(learning_rate=1e-2, beta1=0.9, beta2=0.999,
               epsilon=1e-8),
          torch.optim.Adam, dict(lr=1e-2, betas=(0.9, 0.999),
                                 eps=1e-8),
          paddle.optimizer.Adam)


def test_adamw_matches_torch():
    _pair(dict(learning_rate=1e-2, beta1=0.9, beta2=0.999,
               epsilon=1e-8, weight_decay=0.05),
          torch.optim.AdamW, dict(lr=1e-2, betas=(0.9, 0.999),
                                  eps=1e-8, weight_decay=0.05),
          paddle.optimizer.AdamW)


def test_adagrad_matches_torch():
    _pair(dict(learning_rate=0.05, initial_accumulator_value=0.0,
               epsilon=1e-10),
          torch.optim.Adagrad, dict(lr=0.05, eps=1e-10),
          paddle.optimizer.Adagrad)


def test_rmsprop_matches_paddle_formula():
    """paddle's RMSProp puts epsilon INSIDE the sqrt (rmsprop.py:62:
    w -= lr*g/sqrt(ms + eps)) — torch puts it outside, so the oracle
    here is the paddle formula in numpy."""
    w = rs.randn(4, 3).astype(np.float32)
    grads = [rs.randn(4, 3).astype(np.float32) for _ in range(4)]
    lin = nn.Linear(4, 3, bias_attr=False)
    lin.weight._assign_array(paddle.to_tensor(w)._data)
    opt = paddle.optimizer.RMSProp(learning_rate=0.01, rho=0.99,
                                   epsilon=1e-8,
                                   parameters=lin.parameters())
    ms = np.zeros_like(w)
    ref = w.copy()
    for g in grads:
        lin.weight.clear_grad()
        lin.weight.grad = paddle.to_tensor(g)
        opt.step()
        ms = 0.99 * ms + 0.01 * g * g
        ref -= 0.01 * g / np.sqrt(ms + 1e-8)
    np.testing.assert_allclose(lin.weight.numpy(), ref, rtol=2e-5,
                               atol=2e-6)
