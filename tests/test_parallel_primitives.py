"""Ring attention + ppermute pipeline on the 8-device virtual CPU mesh."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _mesh(n, name):
    return Mesh(np.array(jax.devices()[:n]), (name,))


def _ref_attention(q, k, v, causal):
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if causal:
        s = q.shape[1]
        mask = np.tril(np.ones((s, s), bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    from paddle_tpu.parallel import ring_attention
    from paddle_tpu.parallel.ring_attention import ring_attention_sharded
    rng = np.random.RandomState(0)
    b, s, h, d = 2, 32, 4, 16
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    mesh = _mesh(4, "sp")
    out = ring_attention_sharded(q, k, v, mesh, "sp", causal=causal)
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_attention_grads_match_dense():
    from paddle_tpu.parallel.ring_attention import ring_attention_sharded
    rng = np.random.RandomState(1)
    b, s, h, d = 1, 16, 2, 8
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    mesh = _mesh(4, "sp")

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh, "sp",
                                              causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref_attention(q, k, v, True) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=3e-4, atol=3e-4)


def test_pipeline_apply_matches_sequential():
    from functools import partial
    from jax import shard_map
    from paddle_tpu.parallel import pipeline_apply, stack_stage_params
    from paddle_tpu.parallel.pipeline import pipeline_microbatch

    n_stages = 4
    mesh = _mesh(n_stages, "pp")
    rng = np.random.RandomState(0)
    dim = 8
    stage_ws = [jnp.asarray(rng.randn(dim, dim) * 0.3, jnp.float32)
                for _ in range(n_stages)]
    stacked = stack_stage_params([{"w": w} for w in stage_ws])

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"])

    x = jnp.asarray(rng.randn(8, 4, dim), jnp.float32)  # [M=8, B=4, dim]

    pipe = shard_map(
        partial(pipeline_apply, stage_fn, axis_name="pp"),
        mesh=mesh,
        in_specs=({"w": P("pp", None, None)}, P(None)),
        out_specs=P(None))
    out = pipe(stacked, x)

    ref = x
    for w in stage_ws:
        ref = jnp.tanh(ref @ w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_grad_flows():
    from functools import partial
    from jax import shard_map
    from paddle_tpu.parallel import pipeline_apply, stack_stage_params

    n_stages = 2
    mesh = _mesh(n_stages, "pp")
    rng = np.random.RandomState(0)
    dim = 4
    stage_ws = [jnp.asarray(rng.randn(dim, dim) * 0.3, jnp.float32)
                for _ in range(n_stages)]
    stacked = stack_stage_params([{"w": w} for w in stage_ws])

    def stage_fn(params, x):
        return jnp.tanh(x @ params["w"])

    x = jnp.asarray(rng.randn(4, 2, dim), jnp.float32)

    def loss(params):
        pipe = shard_map(
            partial(pipeline_apply, stage_fn, axis_name="pp"),
            mesh=mesh,
            in_specs=({"w": P("pp", None, None)}, P(None)),
            out_specs=P(None))
        return jnp.sum(pipe(params, x) ** 2)

    def ref_loss(params):
        ref = x
        for i in range(n_stages):
            ref = jnp.tanh(ref @ params["w"][i])
        return jnp.sum(ref ** 2)

    g = jax.grad(loss)(stacked)
    g_ref = jax.grad(ref_loss)(stacked)
    np.testing.assert_allclose(np.asarray(g["w"]),
                               np.asarray(g_ref["w"]),
                               rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# Ulysses all-to-all sequence parallelism
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(causal):
    from paddle_tpu.parallel import ulysses_attention_sharded
    rng = np.random.RandomState(0)
    b, s, h, d = 2, 32, 4, 16
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    mesh = _mesh(4, "sp")
    out = ulysses_attention_sharded(q, k, v, mesh, "sp", causal=causal)
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_grads_match_dense():
    from paddle_tpu.parallel import ulysses_attention_sharded
    rng = np.random.RandomState(1)
    b, s, h, d = 1, 16, 4, 8
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    mesh = _mesh(4, "sp")

    def loss_u(q, k, v):
        return jnp.sum(
            ulysses_attention_sharded(q, k, v, mesh, "sp", causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref_attention(q, k, v, True) ** 2)

    g_u = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_u, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-4)


def test_ulysses_rejects_indivisible_heads():
    from paddle_tpu.parallel import ulysses_attention_sharded
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 16, 3, 8), jnp.float32)  # 3 heads, sp=4
    mesh = _mesh(4, "sp")
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention_sharded(q, q, q, mesh, "sp")


# ---------------------------------------------------------------------------
# Sequence-parallel TP linears (fleet sequence_parallel_utils)
# ---------------------------------------------------------------------------
def test_sequence_parallel_linears_match_plain():
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed.fleet import (
        ColumnSequenceParallelLinear, RowSequenceParallelLinear)
    from paddle_tpu.distributed.fleet.sequence_parallel_utils import (
        all_gather, scatter)
    from paddle_tpu.distributed.mesh import ProcessMesh, set_mesh

    mesh = ProcessMesh(shape=[4], dim_names=["mp"])
    set_mesh(mesh)
    try:
        paddle.seed(7)
        col = ColumnSequenceParallelLinear(16, 32, gather_output=False)
        row = RowSequenceParallelLinear(32, 16, input_is_parallel=True)
        ref1 = nn.Linear(16, 32)
        ref2 = nn.Linear(32, 16)
        ref1.weight.set_value(paddle.to_tensor(col.weight.numpy()))
        ref1.bias.set_value(paddle.to_tensor(col.bias.numpy()))
        ref2.weight.set_value(paddle.to_tensor(row.weight.numpy()))
        ref2.bias.set_value(paddle.to_tensor(row.bias.numpy()))
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 8, 16).astype("float32"))
        xs = scatter(x)                      # [B, S/mp, 16]
        y = all_gather(row(col(xs)))         # back to replicated
        expect = ref2(ref1(x))
        np.testing.assert_allclose(y.numpy(), expect.numpy(),
                                   rtol=1e-4, atol=1e-5)
    finally:
        set_mesh(None)
