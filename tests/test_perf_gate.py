"""Perf/footprint regression gate for the flagship training program.

Round 3 shipped a silent moment-dtype regression (f32 moments under the
bf16-param flagship = +5.2 GB = OOM cascade on the 16 GB chip) that the
884-test suite never saw, because nothing constrained the flagship
program's footprint. These gates pin the invariants on CPU, in seconds:

  - optimizer state INHERITS the param dtype under moment_dtype=None
    (the `zeros_like` contract every recorded bench number ran under)
  - total train-state bytes (params + both Adam moments) of the 1.3B
    flagship stay inside a golden budget — eval_shape only, no memory
  - the jitted train step's executable cache stays at ONE entry across
    repeated same-shape steps (recompile = silent 20-40 s/step cliff)
  - the gradient-merge step does not widen the accumulator beyond the
    param dtype (a second place a dtype default could silently double
    HBM)

Reference analog: the CI op-benchmark regression gate
(/root/reference/tools/ci_op_benchmark.sh) — an automated tripwire, not
a human remembering to re-measure.

RATIO-BASED rungs (ISSUE 13): BENCH_r05 showed the absolute decode
number sits inside a 129-480 tokens/s transport-weather band — an
absolute pin would either gate nothing or cry wolf. The gate therefore
pins WITHIN-WINDOW RATIOS (two quantities measured in the same
capture: s4096/s1024 MFU, dataloader-fed/pinned, cb/per-step-decode)
and telemetry-derived invariants read from the registry snapshot each
BENCH json now embeds under its ``telemetry`` key. Absolute
throughputs are reported informationally only — they are NOT asserted.
"""
import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models import gpt_hybrid as GH
from paddle_tpu.observability import Snapshot

FLAGSHIP = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                     num_heads=16, max_seq_len=1024)


def _flagship_pcfg(**over):
    base = dict(dp=1, pp=1, tp=1, remat=True, remat_policy="names",
                scan_unroll=1, param_dtype=jnp.bfloat16,
                compute_dtype=jnp.bfloat16, moment_dtype=None)
    base.update(over)
    return GH.ParallelConfig(**base)


def _state_shapes(pcfg):
    """abstract (params, opt_state) of the flagship — no arrays made."""
    def build():
        params = GH.init_params(FLAGSHIP, pcfg, jax.random.PRNGKey(0))
        # dp==1: adamw_init's zero1 sharding branch is dead, mesh unused
        opt = GH.adamw_init(params, pcfg, mesh=None, specs=None)
        return params, opt
    return jax.eval_shape(build)


def _tree_bytes(tree):
    return sum(int(np.prod(l.shape)) * l.dtype.itemsize
               for l in jax.tree_util.tree_leaves(tree))


def test_moments_inherit_param_dtype():
    params, opt = _state_shapes(_flagship_pcfg())
    pleaves = jax.tree_util.tree_leaves(params)
    for name in ("m", "v"):
        mleaves = jax.tree_util.tree_leaves(opt[name])
        assert len(mleaves) == len(pleaves)
        for p, mo in zip(pleaves, mleaves):
            assert mo.dtype == p.dtype, (
                f"moment '{name}' dtype {mo.dtype} != param dtype "
                f"{p.dtype} under moment_dtype=None — this is the "
                "round-3 +5.2 GB regression")


def test_flagship_state_bytes_within_budget():
    # bf16 1.3B: params ~2.63 GB, m ~2.63, v ~2.63 => ~7.9 GB.
    # f32 moments push this to ~13.2 GB and must FAIL here.
    params, opt = _state_shapes(_flagship_pcfg())
    total = _tree_bytes(params) + _tree_bytes(opt["m"]) + \
        _tree_bytes(opt["v"])
    budget = 8.5e9
    assert total < budget, (
        f"flagship train state {total/1e9:.2f} GB exceeds the golden "
        f"{budget/1e9:.1f} GB budget (param+moment dtype widened?)")
    # and the explicit-f32 config is provably over — the gate is live
    _, opt32 = _state_shapes(_flagship_pcfg(moment_dtype=jnp.float32))
    total32 = _tree_bytes(params) + _tree_bytes(opt32["m"]) + \
        _tree_bytes(opt32["v"])
    assert total32 > budget


def test_train_step_executable_count_stable():
    """Steady-state calls of the jitted train step must neither
    RE-TRACE nor RE-COMPILE (a recompile = silent 20-40 s/step cliff).

    Asserted via the framework's compile-cache tracker over calls
    2..4, NOT via PjitFunction._cache_size(): the C++ fastpath-cache
    entry count measures whether jaxlib *installed its dispatch
    fastpath*, which late in a long test session can legitimately be
    declined (observed deterministically after ~750 suite tests with
    zero retraces, zero recompiles, clean config and an effect-free
    jaxpr — a jaxlib dispatch-layer heuristic, not a program
    regression). Counting actual tracing/compilation events pins the
    invariant that matters and is order-independent. (Formerly used
    jtu.count_jit_*_cache_miss, whose yielded object drifted from a
    callable to a bare list across jax versions —
    observability.count_traces/count_compiles is the stable
    framework-owned surface.)"""
    from paddle_tpu import observability as obs
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=2, max_seq_len=64)
    pcfg = _flagship_pcfg(param_dtype=jnp.float32,
                          compute_dtype=jnp.float32)
    mesh, params, opt_state, step = GH.setup(cfg, pcfg, seed=0,
                                             devices=jax.devices()[:1])
    ids = jnp.zeros((2, 32), jnp.int32)
    with mesh:
        # warmup call pays the one allowed trace+compile
        params, opt_state, loss = step(params, opt_state, (ids, ids))
        with obs.count_traces() as traces, \
                obs.count_compiles() as compiles:
            for _ in range(3):
                params, opt_state, loss = step(params, opt_state,
                                               (ids, ids))
    assert traces() == 0 and compiles() == 0, (
        f"steady-state train-step calls re-traced {traces()}x / "
        f"re-compiled {compiles()}x — donation/weak-type/sharding "
        "drift is forcing recompiles")
    # liveness: the counters must SEE a genuine recompile (new shape),
    # or the zero above proves nothing
    with mesh:
        with obs.count_traces() as traces2:
            ids2 = jnp.zeros((4, 32), jnp.int32)
            params, opt_state, loss = step(params, opt_state,
                                           (ids2, ids2))
    assert traces2() > 0, "counter failed to observe a real retrace"


def test_gradient_merge_accumulator_dtype():
    pcfg = _flagship_pcfg(gradient_merge_steps=4)
    params, _ = _state_shapes(pcfg)
    # the merge accumulator is zeros_like(params) inside the scan —
    # assert the public contract at the init helper that feeds the
    # split-engine path (same zeros_like rule)
    acc = jax.eval_shape(
        lambda: GH.init_grad_accum(
            jax.eval_shape(lambda: GH.init_params(
                FLAGSHIP, pcfg, jax.random.PRNGKey(0)))))
    for a, p in zip(jax.tree_util.tree_leaves(acc),
                    jax.tree_util.tree_leaves(params)):
        assert a.dtype == p.dtype
    # decode's executable-count stability is gated in
    # tests/test_decode.py::test_decode_executable_stability


# ===================================================================
# Ratio-based regression rungs (ISSUE 13). Bands are anchored on the
# BENCH_r05 on-device capture and NOTES.md Round-6:
#   * s4096/s1024 MFU ratio: 0.870 pre-blocked-kernel (0.5897/0.6779);
#     the kernel only dispatches where it measures faster, so the
#     floor is pre-kernel-minus-margin. (The 0.62-MFU roofline target
#     corresponds to ratio ~0.915 — reported, not yet pinned: it is
#     the thing the next capture must resolve.)
#   * s2048/s1024: 0.929 recorded -> floor 0.87.
#   * dataloader-fed vs pinned batch: 1.007 recorded -> floor 0.97
#     (the loader must not throttle the step); ceiling 1.10 catches a
#     formula bug (the loader cannot beat a pinned batch by 10%).
#   * cb vs per-step decode, SAME window: 1.83 recorded; the per-step
#     leg is RTT-dominated so good transport compresses the ratio —
#     floor 0.8 only trips when continuous batching falls below the
#     naive path it exists to beat.
RATIO_RUNGS = {
    "train_s4096.mfu_ratio_vs_s1024": (0.82, 1.05),
    "train_s2048.mfu_ratio_vs_s1024": (0.87, 1.10),
    "train_dataloader_fed.vs_pinned_batch": (0.97, 1.10),
    "serve_cb_block16.vs_decode_b8": (0.80, 6.0),
}

#: trace-time analytic bubble fraction ceiling per schedule family
#: (read from the BENCH json's embedded telemetry snapshot)
BUBBLE_CEILING = {"zbh1": 0.2, "zbvpp": 0.2}
BUBBLE_CEILING_DEFAULT = 0.5

S4096_MFU_TARGET = 0.62   # NOTES.md Round-6 roofline question


def check_ratio_rungs(parsed):
    """Gate one parsed BENCH document. Returns (checked, failures,
    missing): ``checked`` maps every rung that was present to its
    value, ``failures`` lists band violations, ``missing`` names rungs
    this capture did not carry (informational — older captures predate
    some rungs). Absolute throughputs are never asserted here."""
    checked, failures, missing = {}, [], []
    rungs = parsed.get("rungs") or {}
    for name, (lo, hi) in RATIO_RUNGS.items():
        rung_name, key = name.split(".")
        v = rungs.get(rung_name) or {}
        v = v.get(key) if isinstance(v, dict) else None
        if v is None:
            missing.append(name)
            continue
        checked[name] = v
        if lo is not None and v < lo:
            failures.append(f"{name}={v} below floor {lo}")
        if hi is not None and v > hi:
            failures.append(f"{name}={v} above ceiling {hi}")

    # --- telemetry-derived rungs: the registry snapshot embedded in
    # the same artifact (bench.py writes it under "telemetry")
    tel = (parsed.get("telemetry") or {}).get("metrics")
    if tel is None:
        missing.append("telemetry")
        return checked, failures, missing
    snap = Snapshot.from_metrics(tel)
    for d in snap.series("pipeline.bubble_fraction"):
        sched = (d.get("labels") or {}).get("schedule", "?")
        name = f"telemetry.bubble_fraction[{sched}]"
        val = d.get("value", 0.0)
        checked[name] = val
        ceil = BUBBLE_CEILING.get(sched, BUBBLE_CEILING_DEFAULT)
        if not (0.0 <= val <= ceil):
            failures.append(f"{name}={val} outside [0, {ceil}]")
    # a measured long-context rung must have gone through the
    # instrumented dispatch chain — the kernel choice is recorded, not
    # inferred
    s4096 = rungs.get("train_s4096") or {}
    if "mfu" in s4096:
        n_disp = sum(d.get("value", 0)
                     for d in snap.series("attn.dispatch"))
        name = "telemetry.attn_dispatches"
        checked[name] = n_disp
        if n_disp <= 0:
            failures.append(
                f"{name}: s4096 measured but no attn.dispatch "
                "counters in the embedded snapshot")
    return checked, failures, missing


def _bench_docs_newest_first():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    docs = []
    for p in glob.glob(os.path.join(root, "BENCH_*.json")):
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed")
        if isinstance(parsed, dict):
            docs.append((doc.get("n", 0), os.path.basename(p), parsed))
    docs.sort(key=lambda t: -t[0])
    return docs


def test_ratio_gate_trips_and_passes(tmp_path):
    """The gate logic itself, against a BENCH json on disk (the exact
    read path the real artifacts take): a healthy capture passes every
    rung including the telemetry-derived ones; a regressed capture
    fails on the regressed rungs and ONLY those."""
    telemetry = {"ts": 0.0, "metrics": [
        {"name": "pipeline.bubble_fraction", "type": "gauge",
         "labels": {"schedule": "1f1b"}, "value": 0.27},
        {"name": "pipeline.bubble_fraction", "type": "gauge",
         "labels": {"schedule": "zbh1"}, "value": 0.03},
        {"name": "attn.dispatch", "type": "counter",
         "labels": {"kernel": "blocked_bq512_bkv512"}, "value": 2.0},
        {"name": "train.mfu", "type": "gauge", "labels": {},
         "value": 0.63},
    ]}
    good = {
        "metric": "gpt1.3b_train_tokens_per_sec_per_chip",
        "value": 15736.8, "mfu": 0.6779,
        "rungs": {
            "train_s2048": {"mfu": 0.6295,
                            "mfu_ratio_vs_s1024": 0.9286},
            "train_s4096": {"mfu": 0.63, "mfu_ratio_vs_s1024": 0.9294,
                            "attn_kernel": "blocked_bq512_bkv512"},
            "train_dataloader_fed": {"vs_pinned_batch": 1.0066},
            "serve_cb_block16": {"tokens_per_sec": 423.3,
                                 "vs_decode_b8": 1.832},
            "decode_gpt1.3b_b8": {"tokens_per_sec": 231.1},
        },
        "telemetry": telemetry,
    }
    p = tmp_path / "BENCH_synthetic.json"
    p.write_text(json.dumps({"n": 99, "parsed": good}))
    parsed = json.loads(p.read_text())["parsed"]
    checked, failures, missing = check_ratio_rungs(parsed)
    assert not failures, failures
    assert not missing
    # >= 3 ratio rungs pinned, the headline one among them, plus the
    # telemetry-derived bubble/dispatch invariants
    assert len([k for k in checked if k in RATIO_RUNGS]) >= 3
    assert "train_s4096.mfu_ratio_vs_s1024" in checked
    assert "telemetry.bubble_fraction[1f1b]" in checked
    assert "telemetry.attn_dispatches" in checked

    # regressed capture: s4096 ratio collapses, zbh1 bubble explodes,
    # cb falls below the naive decode path
    bad = json.loads(json.dumps(good))
    bad["rungs"]["train_s4096"]["mfu_ratio_vs_s1024"] = 0.70
    bad["rungs"]["serve_cb_block16"]["vs_decode_b8"] = 0.5
    bad["telemetry"]["metrics"][1]["value"] = 0.35   # zbh1 bubble
    _, failures, _ = check_ratio_rungs(bad)
    assert len(failures) == 3, failures
    assert any("train_s4096" in f for f in failures)
    assert any("vs_decode_b8" in f for f in failures)
    assert any("zbh1" in f for f in failures)

    # a capture missing a rung reports it missing — never a false trip
    sparse = {"rungs": {"train_dataloader_fed":
                        {"vs_pinned_batch": 1.0}}}
    checked, failures, missing = check_ratio_rungs(sparse)
    assert not failures
    assert "train_s4096.mfu_ratio_vs_s1024" in missing
    assert "telemetry" in missing


def test_recorded_bench_ratios_within_bands():
    """Gate the real recorded BENCH artifacts: for each ratio rung,
    the NEWEST capture that carries it must sit inside its band.
    Rungs no capture carries yet are reported (the next on-device run
    fills them); at least one must already be live so the gate is
    provably wired to real artifacts. Absolute throughputs print
    informationally and are NOT asserted."""
    docs = _bench_docs_newest_first()
    assert docs, "no BENCH_*.json artifacts found at repo root"
    newest = docs[0][2]
    print(f"[perf_gate] informational absolutes (newest capture): "
          f"value={newest.get('value')} {newest.get('unit', '')} "
          f"mfu={newest.get('mfu')}")
    gated, all_failures, still_missing = {}, [], set(RATIO_RUNGS)
    for _n, fname, parsed in docs:
        checked, failures, _missing = check_ratio_rungs(parsed)
        fresh = {k: v for k, v in checked.items() if k not in gated}
        if not fresh:
            continue
        for k, v in fresh.items():
            gated[k] = (v, fname)
        still_missing -= set(fresh)
        # only failures for rungs this doc is the newest carrier of
        all_failures += [f for f in failures
                         if any(k in f for k in fresh)]
    assert not all_failures, all_failures
    assert gated, "no ratio rung found in any recorded BENCH json"
    if still_missing:
        print(f"[perf_gate] rungs awaiting their first capture: "
              f"{sorted(still_missing)}")
    # the r05 capture already carries the dataloader ratio — the gate
    # must be LIVE against today's artifacts, not only future ones
    assert "train_dataloader_fed.vs_pinned_batch" in gated
