"""Perf/footprint regression gate for the flagship training program.

Round 3 shipped a silent moment-dtype regression (f32 moments under the
bf16-param flagship = +5.2 GB = OOM cascade on the 16 GB chip) that the
884-test suite never saw, because nothing constrained the flagship
program's footprint. These gates pin the invariants on CPU, in seconds:

  - optimizer state INHERITS the param dtype under moment_dtype=None
    (the `zeros_like` contract every recorded bench number ran under)
  - total train-state bytes (params + both Adam moments) of the 1.3B
    flagship stay inside a golden budget — eval_shape only, no memory
  - the jitted train step's executable cache stays at ONE entry across
    repeated same-shape steps (recompile = silent 20-40 s/step cliff)
  - the gradient-merge step does not widen the accumulator beyond the
    param dtype (a second place a dtype default could silently double
    HBM)

Reference analog: the CI op-benchmark regression gate
(/root/reference/tools/ci_op_benchmark.sh) — an automated tripwire, not
a human remembering to re-measure.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models import gpt_hybrid as GH

FLAGSHIP = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                     num_heads=16, max_seq_len=1024)


def _flagship_pcfg(**over):
    base = dict(dp=1, pp=1, tp=1, remat=True, remat_policy="names",
                scan_unroll=1, param_dtype=jnp.bfloat16,
                compute_dtype=jnp.bfloat16, moment_dtype=None)
    base.update(over)
    return GH.ParallelConfig(**base)


def _state_shapes(pcfg):
    """abstract (params, opt_state) of the flagship — no arrays made."""
    def build():
        params = GH.init_params(FLAGSHIP, pcfg, jax.random.PRNGKey(0))
        # dp==1: adamw_init's zero1 sharding branch is dead, mesh unused
        opt = GH.adamw_init(params, pcfg, mesh=None, specs=None)
        return params, opt
    return jax.eval_shape(build)


def _tree_bytes(tree):
    return sum(int(np.prod(l.shape)) * l.dtype.itemsize
               for l in jax.tree_util.tree_leaves(tree))


def test_moments_inherit_param_dtype():
    params, opt = _state_shapes(_flagship_pcfg())
    pleaves = jax.tree_util.tree_leaves(params)
    for name in ("m", "v"):
        mleaves = jax.tree_util.tree_leaves(opt[name])
        assert len(mleaves) == len(pleaves)
        for p, mo in zip(pleaves, mleaves):
            assert mo.dtype == p.dtype, (
                f"moment '{name}' dtype {mo.dtype} != param dtype "
                f"{p.dtype} under moment_dtype=None — this is the "
                "round-3 +5.2 GB regression")


def test_flagship_state_bytes_within_budget():
    # bf16 1.3B: params ~2.63 GB, m ~2.63, v ~2.63 => ~7.9 GB.
    # f32 moments push this to ~13.2 GB and must FAIL here.
    params, opt = _state_shapes(_flagship_pcfg())
    total = _tree_bytes(params) + _tree_bytes(opt["m"]) + \
        _tree_bytes(opt["v"])
    budget = 8.5e9
    assert total < budget, (
        f"flagship train state {total/1e9:.2f} GB exceeds the golden "
        f"{budget/1e9:.1f} GB budget (param+moment dtype widened?)")
    # and the explicit-f32 config is provably over — the gate is live
    _, opt32 = _state_shapes(_flagship_pcfg(moment_dtype=jnp.float32))
    total32 = _tree_bytes(params) + _tree_bytes(opt32["m"]) + \
        _tree_bytes(opt32["v"])
    assert total32 > budget


def test_train_step_executable_count_stable():
    """Steady-state calls of the jitted train step must neither
    RE-TRACE nor RE-COMPILE (a recompile = silent 20-40 s/step cliff).

    Asserted via the framework's compile-cache tracker over calls
    2..4, NOT via PjitFunction._cache_size(): the C++ fastpath-cache
    entry count measures whether jaxlib *installed its dispatch
    fastpath*, which late in a long test session can legitimately be
    declined (observed deterministically after ~750 suite tests with
    zero retraces, zero recompiles, clean config and an effect-free
    jaxpr — a jaxlib dispatch-layer heuristic, not a program
    regression). Counting actual tracing/compilation events pins the
    invariant that matters and is order-independent. (Formerly used
    jtu.count_jit_*_cache_miss, whose yielded object drifted from a
    callable to a bare list across jax versions —
    observability.count_traces/count_compiles is the stable
    framework-owned surface.)"""
    from paddle_tpu import observability as obs
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=2, max_seq_len=64)
    pcfg = _flagship_pcfg(param_dtype=jnp.float32,
                          compute_dtype=jnp.float32)
    mesh, params, opt_state, step = GH.setup(cfg, pcfg, seed=0,
                                             devices=jax.devices()[:1])
    ids = jnp.zeros((2, 32), jnp.int32)
    with mesh:
        # warmup call pays the one allowed trace+compile
        params, opt_state, loss = step(params, opt_state, (ids, ids))
        with obs.count_traces() as traces, \
                obs.count_compiles() as compiles:
            for _ in range(3):
                params, opt_state, loss = step(params, opt_state,
                                               (ids, ids))
    assert traces() == 0 and compiles() == 0, (
        f"steady-state train-step calls re-traced {traces()}x / "
        f"re-compiled {compiles()}x — donation/weak-type/sharding "
        "drift is forcing recompiles")
    # liveness: the counters must SEE a genuine recompile (new shape),
    # or the zero above proves nothing
    with mesh:
        with obs.count_traces() as traces2:
            ids2 = jnp.zeros((4, 32), jnp.int32)
            params, opt_state, loss = step(params, opt_state,
                                           (ids2, ids2))
    assert traces2() > 0, "counter failed to observe a real retrace"


def test_gradient_merge_accumulator_dtype():
    pcfg = _flagship_pcfg(gradient_merge_steps=4)
    params, _ = _state_shapes(pcfg)
    # the merge accumulator is zeros_like(params) inside the scan —
    # assert the public contract at the init helper that feeds the
    # split-engine path (same zeros_like rule)
    acc = jax.eval_shape(
        lambda: GH.init_grad_accum(
            jax.eval_shape(lambda: GH.init_params(
                FLAGSHIP, pcfg, jax.random.PRNGKey(0)))))
    for a, p in zip(jax.tree_util.tree_leaves(acc),
                    jax.tree_util.tree_leaves(params)):
        assert a.dtype == p.dtype
    # decode's executable-count stability is gated in
    # tests/test_decode.py::test_decode_executable_stability
