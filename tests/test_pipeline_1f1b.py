"""Compiled 1F1B pipeline (parallel/pipeline_1f1b.py).

Checks, per VERDICT round-1 item 6:
  1. numerics — loss and ALL gradients (stage params, head params,
     stage-0 input cotangents) match plain jax autodiff of the
     sequential composition;
  2. schedule equivalence — the compiled timeline validates under
     pp_schedule's dependency simulator and its peak-activation count
     is bounded by 2N-1 independent of M (vs M for GPipe/F-then-B);
  3. the bound beats GPipe's for M > 2(N-1)+1.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from paddle_tpu.parallel.pipeline_1f1b import (compiled_1f1b_schedule,
                                               pipeline_train_1f1b)
from paddle_tpu.parallel.pipeline import stack_stage_params
from paddle_tpu.parallel.pp_schedule import schedule_fthenb

N_STAGES = 4
HID = 8


def _stage_fn(params, x):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + x


def _head(y, wh, targets_mb):
    # mean-square head with a parameter, per microbatch
    pred = y @ wh
    return jnp.mean((pred - targets_mb) ** 2)


def _make(m, seed=0):
    rng = np.random.RandomState(seed)
    stages = [{"w1": jnp.asarray(rng.randn(HID, HID) * 0.3,
                                 jnp.float32),
               "b1": jnp.asarray(rng.randn(HID) * 0.1, jnp.float32),
               "w2": jnp.asarray(rng.randn(HID, HID) * 0.3,
                                 jnp.float32)}
              for _ in range(N_STAGES)]
    wh = jnp.asarray(rng.randn(HID, 3) * 0.4, jnp.float32)
    mb = jnp.asarray(rng.randn(m, 2, HID), jnp.float32)
    tgt = jnp.asarray(rng.randn(m, 2, 3), jnp.float32)
    return stages, wh, mb, tgt


def _oracle(stages, wh, mb, tgt):
    """Plain autodiff of the sequential composition, summed over M."""
    def total_loss(stages, wh, x0):
        def per_mb(x, t):
            for p in stages:
                x = _stage_fn(p, x)
            return _head(x, wh, t)
        return sum(per_mb(mb[i], tgt[i]) for i in range(mb.shape[0]))

    loss, grads = jax.value_and_grad(total_loss, argnums=(0, 1))(
        stages, wh, mb)
    # input cotangents at stage 0
    def loss_of_x(x0):
        def per(x, t):
            for p in stages:
                x = _stage_fn(p, x)
            return _head(x, wh, t)
        return sum(per(x0[i], tgt[i]) for i in range(mb.shape[0]))
    dx0 = jax.grad(loss_of_x)(mb)
    return loss, grads[0], grads[1], dx0


@pytest.mark.parametrize("m", [4, 8])
def test_1f1b_matches_autodiff_oracle(m):
    stages, wh, mb, tgt = _make(m)
    devices = jax.devices()[:N_STAGES]
    mesh = Mesh(np.asarray(devices), ("pp",))
    stacked = stack_stage_params(stages)

    def body(stacked, mb, tgt, wh):
        def last_grad(y, hp, mb_idx):
            t = tgt[mb_idx]        # replicated labels by microbatch id
            def head_loss(wh_, y_):
                return _head(y_, wh_, t)
            (loss, (gwh, gy)) = jax.value_and_grad(
                head_loss, argnums=(0, 1))(hp["wh"], y)
            return loss, gy, {"wh": gwh}
        return pipeline_train_1f1b(_stage_fn, stacked, mb, last_grad,
                                   head_params={"wh": wh})

    specs = jax.tree_util.tree_map(lambda _: P("pp"), stacked)
    loss, grads, head, dx0 = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(specs, P(None), P(None), P(None)),
        out_specs=(P(), specs, P(), P(None))))(stacked, mb, tgt, wh)

    ref_loss, ref_sg, ref_wh, ref_dx0 = _oracle(stages, wh, mb, tgt)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(head["wh"]),
                               np.asarray(ref_wh), rtol=1e-4,
                               atol=1e-5)
    for i in range(N_STAGES):
        got = jax.tree_util.tree_map(lambda g: np.asarray(g[i]), grads)
        for name in ("w1", "b1", "w2"):
            np.testing.assert_allclose(
                got[name], np.asarray(ref_sg[i][name]),
                rtol=1e-4, atol=1e-5, err_msg=f"stage{i}.{name}")
    np.testing.assert_allclose(np.asarray(dx0), np.asarray(ref_dx0),
                               rtol=1e-4, atol=1e-5)


def test_compiled_schedule_validates_and_bounds_memory():
    for n, m in [(4, 8), (4, 32), (2, 4), (8, 16)]:
        sched = compiled_1f1b_schedule(n, m)
        makespan, bubble = sched.simulate()   # raises on bad deps
        assert makespan > 0
        # the liveness bound: 2N-1 independent of M
        assert sched.peak_activations() == min(m, 2 * (n - 1) + 1)
        assert schedule_fthenb(n, m).peak_activations() == m


def test_memory_bound_beats_gpipe_for_deep_m():
    n = 4
    gpipe = schedule_fthenb(n, 32).peak_activations()
    ours = compiled_1f1b_schedule(n, 32).peak_activations()
    assert ours == 7 and gpipe == 32


def test_gpt_hybrid_1f1b_matches_gpipe():
    """The hybrid engine's pp_schedule='1f1b' path trains the same
    model as the gpipe path: identical loss on step 1 and matching
    updated parameters."""
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models.gpt_hybrid import ParallelConfig, setup

    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=4,
                    num_heads=2, max_seq_len=16)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 64, (4, 16)))

    results = {}
    for sched in ("gpipe", "1f1b"):
        pcfg = ParallelConfig(dp=1, pp=4, tp=1, microbatches=4,
                              remat=True, fused_ce=False,
                              pp_schedule=sched,
                              param_dtype=jnp.float32,
                              compute_dtype=jnp.float32)
        mesh, params, opt, step = setup(cfg, pcfg, seed=0,
                                        devices=jax.devices()[:4])
        with mesh:
            new_params, _, loss = step(params, opt, (ids, ids))
        results[sched] = (float(loss), new_params)

    l_g, p_g = results["gpipe"]
    l_f, p_f = results["1f1b"]
    np.testing.assert_allclose(l_f, l_g, rtol=1e-5)
    flat_g = jax.tree_util.tree_leaves(p_g)
    flat_f = jax.tree_util.tree_leaves(p_f)
    for a, b in zip(flat_g, flat_f):
        np.testing.assert_allclose(np.asarray(b).reshape(-1),
                                   np.asarray(a).reshape(-1),
                                   rtol=2e-4, atol=2e-5)
