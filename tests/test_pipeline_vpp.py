"""Compiled interleaved VPP (parallel/pipeline_1f1b.py round-3 addition)
+ ZBVPP descriptor (VERDICT r2 item 5).

  1. numerics — loss + all grads of the v*n-deep virtual pipeline match
     plain autodiff of the sequential composition;
  2. schedule equivalence — the compiled timeline validates under the
     dependency simulator;
  3. ZBVPP descriptor validates with bubble <= fused-backward 1F1B in
     the small-microbatch regime it targets;
  4. the hybrid engine runs vpp_chunks=2 with loss parity vs pp=2 1F1B.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from paddle_tpu.parallel.pipeline_1f1b import (
    compiled_interleaved_schedule, pipeline_train_interleaved)
from paddle_tpu.parallel.pp_schedule import (schedule_1f1b,
                                             schedule_zbvpp)

N_DEV = 2
V = 2
HID = 8


def _stage_fn(params, x):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + x


def _head(y, wh, t):
    return jnp.mean((y @ wh - t) ** 2)


def _make(m, seed=0):
    rng = np.random.RandomState(seed)
    # n*v virtual stages; laid out [n_dev, v, ...] (device, chunk)
    def mk():
        return {"w1": jnp.asarray(rng.randn(HID, HID) * 0.3, jnp.float32),
                "b1": jnp.asarray(rng.randn(HID) * 0.1, jnp.float32),
                "w2": jnp.asarray(rng.randn(HID, HID) * 0.3, jnp.float32)}
    virt = [mk() for _ in range(N_DEV * V)]
    wh = jnp.asarray(rng.randn(HID, 3) * 0.4, jnp.float32)
    mb = jnp.asarray(rng.randn(m, 2, HID), jnp.float32)
    tgt = jnp.asarray(rng.randn(m, 2, 3), jnp.float32)
    return virt, wh, mb, tgt


def _stack_virtual(virt):
    """virtual stage sigma = j*n + s -> stacked leaf [n, v, ...]."""
    out = {}
    for key in virt[0]:
        rows = []
        for s in range(N_DEV):
            rows.append(jnp.stack([virt[j * N_DEV + s][key]
                                   for j in range(V)]))
        out[key] = jnp.stack(rows)          # [n, v, ...]
    return out


def _oracle(virt, wh, mb, tgt):
    def total(virt, wh):
        def per(x, t):
            for p in virt:
                x = _stage_fn(p, x)
            return _head(x, wh, t)
        return sum(per(mb[i], tgt[i]) for i in range(mb.shape[0]))
    loss, (gv, gwh) = jax.value_and_grad(total, argnums=(0, 1))(virt, wh)

    def loss_of_x(x0):
        def per(x, t):
            for p in virt:
                x = _stage_fn(p, x)
            return _head(x, wh, t)
        return sum(per(x0[i], tgt[i]) for i in range(mb.shape[0]))
    dx0 = jax.grad(loss_of_x)(mb)
    return loss, gv, gwh, dx0


@pytest.mark.parametrize("m", [4, 8])
def test_vpp_matches_autodiff_oracle(m):
    virt, wh, mb, tgt = _make(m)
    mesh = Mesh(np.asarray(jax.devices()[:N_DEV]), ("pp",))
    stacked = _stack_virtual(virt)

    def body(stacked, mb, tgt, wh):
        def last_grad(y, hp, mb_idx):
            t = tgt[mb_idx]

            def head_loss(wh_, y_):
                return _head(y_, wh_, t)
            (loss, (gwh, gy)) = jax.value_and_grad(
                head_loss, argnums=(0, 1))(hp["wh"], y)
            return loss, gy, {"wh": gwh}
        return pipeline_train_interleaved(
            _stage_fn, stacked, mb, last_grad, head_params={"wh": wh},
            num_chunks=V)

    specs = jax.tree_util.tree_map(lambda _: P("pp"), stacked)
    loss, grads, head, dx0 = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(specs, P(None), P(None), P(None)),
        out_specs=(P(), specs, P(), P(None))))(stacked, mb, tgt, wh)

    ref_loss, ref_gv, ref_wh, ref_dx0 = _oracle(virt, wh, mb, tgt)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(head["wh"]),
                               np.asarray(ref_wh), rtol=1e-4, atol=1e-5)
    for s in range(N_DEV):
        for j in range(V):
            ref = ref_gv[j * N_DEV + s]
            for name in ("w1", "b1", "w2"):
                np.testing.assert_allclose(
                    np.asarray(grads[name][s, j]), np.asarray(ref[name]),
                    rtol=1e-4, atol=1e-5,
                    err_msg=f"dev{s}.chunk{j}.{name}")
    np.testing.assert_allclose(np.asarray(dx0), np.asarray(ref_dx0),
                               rtol=1e-4, atol=1e-5)


def test_compiled_vpp_schedule_validates():
    for n, m, v in [(2, 4, 2), (4, 8, 2), (2, 8, 3)]:
        sched = compiled_interleaved_schedule(n, m, v)
        makespan, bubble = sched.simulate()   # raises if invalid
        assert makespan > 0
        # every virtual stage's F and B present for every microbatch
        cells = {(o.kind, o.stage, o.mb, o.chunk)
                 for ops in sched.per_stage for o in ops}
        assert len(cells) == 2 * n * m * v


def test_zbvpp_descriptor_validates_and_beats_1f1b_bubble():
    # the small-M regime is where pipeline bubbles matter (M >> n makes
    # any schedule's bubble vanish); ZB targets exactly this regime
    for n, m in [(2, 4), (4, 8), (4, 16), (8, 16)]:
        z = schedule_zbvpp(n, m)
        _, bub = z.simulate()
        _, bub1 = schedule_1f1b(n, m).simulate()
        assert bub <= bub1 + 1e-9, (n, m, bub, bub1)
        # B/W split exists
        kinds = {o.kind for ops in z.per_stage for o in ops}
        assert kinds == {"F", "B", "W"}


def test_hybrid_engine_vpp_matches_1f1b():
    """ParallelConfig.vpp_chunks=2 on pp=2: same loss and params as the
    plain 1F1B schedule (8-dev CPU mesh, 2 pipeline devices)."""
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models.gpt_hybrid import ParallelConfig, setup

    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=4,
                    num_heads=2, max_seq_len=16)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 64, (4, 16)))

    results = {}
    for tag, kw in [("1f1b", dict(pp_schedule="1f1b")),
                    ("vpp", dict(pp_schedule="1f1b", vpp_chunks=2))]:
        pcfg = ParallelConfig(dp=1, pp=2, tp=1, microbatches=4,
                              remat=True, fused_ce=False,
                              param_dtype=jnp.float32,
                              compute_dtype=jnp.float32, **kw)
        mesh, params, opt, step = setup(cfg, pcfg, seed=0,
                                        devices=jax.devices()[:2])
        with mesh:
            new_params, _, loss = step(params, opt, (ids, ids))
        results[tag] = (float(loss), new_params)

    l1, p1 = results["1f1b"]
    l2, p2 = results["vpp"]
    np.testing.assert_allclose(l2, l1, rtol=1e-5)
    # storage orders differ: 1f1b blocks are [pp, L/pp, ...] (layer =
    # s*L/pp + i); vpp blocks are [pp, v, Lc, ...] with virtual stage
    # j*pp + s owning layers [(j*pp+s)*Lc, ...) — compare per LAYER
    for key in p1["blocks"]:
        a = np.asarray(p1["blocks"][key])
        L = a.shape[0] * a.shape[1]
        a = a.reshape((L,) + a.shape[2:])
        b = np.asarray(p2["blocks"][key])
        b = b.swapaxes(0, 1).reshape((L,) + b.shape[3:])
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5,
                                   err_msg=key)
    for key in ("wte", "wpe", "lnf_g", "lnf_b"):
        np.testing.assert_allclose(np.asarray(p1[key]),
                                   np.asarray(p2[key]), rtol=2e-4,
                                   atol=2e-5, err_msg=key)


def test_forward_hidden_eval_under_vpp():
    """VERDICT r3 item 7: eval (forward_hidden) runs on a vpp_chunks>1
    config by relaying out the interleaved [pp, v, Lc] stacking — you
    can now evaluate the config you train."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=8,
                    num_heads=2, max_seq_len=32)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (4, 32)))
    outs = {}
    for v in (1, 4):
        pcfg = GH.ParallelConfig(dp=1, pp=2, tp=1, microbatches=2,
                                 pp_schedule="1f1b", vpp_chunks=v,
                                 remat=True,
                                 param_dtype=jnp.float32,
                                 compute_dtype=jnp.float32)
        mesh = GH.build_mesh(pcfg, jax.devices()[:2])
        with mesh:
            params = GH.init_params(cfg, pcfg, jax.random.PRNGKey(0))
            params, _ = GH.shard_params(params, mesh, cfg, pcfg)
            h = GH.forward_hidden(params, ids, cfg, pcfg, mesh)
        outs[v] = np.asarray(h)
    np.testing.assert_allclose(outs[1], outs[4], rtol=1e-5, atol=1e-6)
