"""Zero-bubble schedules UNDER tp>1 — the round-5 capability.

The reference's ZBH1/ZBVPP passes schedule under any hybrid strategy
(mp collectives inside a chunk are just host-issued ops,
pipeline_zero_bubble.py:62,:151). The compiled analogs compose with
tp>1 through the manual-tp stage body (models/gpt_manual_tp.py):
explicit collectives over a manual 'tp' axis inside the cond-gated
phases, legal because the phase predicates vary only over 'pp'.

Parity oracle: the GSPMD-auto 1F1B path on the SAME params/batch —
both paths must compute the identical loss and grads (f32 here so the
comparison is tight).
"""
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models import gpt_hybrid as GH


CFG = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                num_heads=4, max_seq_len=32, ffn_mult=2)


def _flat_blocks(grads, pcfg, cfg):
    """Reassemble stage-stacked block grads to the flat [L, ...] layout
    (handles the linear and ZB-V stackings)."""
    def fix(x):
        x = np.asarray(x)
        if pcfg.pp_schedule == "zbvpp":
            npp, L = pcfg.pp, cfg.num_layers
            ds = np.concatenate([np.arange(npp),
                                 np.arange(npp - 1, -1, -1)])
            ls = np.concatenate([np.zeros(npp, np.int64),
                                 np.ones(npp, np.int64)])
            return x[ds, ls].reshape((L,) + x.shape[3:])
        return x.reshape((-1,) + x.shape[2:])
    return {k: fix(v) for k, v in grads["blocks"].items()}


def _run(pcfg, cfg=CFG):
    key = jax.random.PRNGKey(0)
    ids = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                             cfg.vocab_size)
    batch = (ids, ids)
    mesh = GH.build_mesh(pcfg)
    params = GH.init_params(cfg, pcfg, key)
    params, _specs = GH.shard_params(params, mesh, cfg, pcfg)
    with mesh:
        loss, grads = jax.jit(
            lambda p, b: GH._train_grads_1f1b(p, b, cfg, pcfg, mesh))(
                params, batch)
        loss.block_until_ready()
    return float(loss), {
        **_flat_blocks(grads, pcfg, cfg),
        "wte": np.asarray(grads["wte"]),
        "wpe": np.asarray(grads["wpe"]),
        "lnf_g": np.asarray(grads["lnf_g"]),
        "lnf_b": np.asarray(grads["lnf_b"]),
    }


def _parity(sched, sp, dp=1, cfg=CFG, cm=False):
    pk = dict(dp=dp, tp=2, pp=2, sp=sp, microbatches=4,
              param_dtype=jnp.float32, compute_dtype=jnp.float32,
              fused_ce=False, remat=True)
    # the oracle is always the GSPMD-auto 1F1B (no ring): the ring
    # collective matmuls must compute the same function
    rl, rg = _run(GH.ParallelConfig(pp_schedule="1f1b", **pk), cfg)
    zl, zg = _run(GH.ParallelConfig(pp_schedule=sched,
                                    collective_matmul=cm, **pk), cfg)
    np.testing.assert_allclose(zl, rl, rtol=2e-5)
    for k in rg:
        np.testing.assert_allclose(zg[k], rg[k], rtol=2e-4, atol=2e-5,
                                   err_msg=k)


@pytest.mark.parametrize("sp", [False, True])
def test_zbh1_tp2_matches_gspmd_1f1b(sp):
    """ZBH1 with a tp=2 stage body (explicit in-branch psums; sp adds
    all_gather/psum_scatter) computes the same loss+grads as the
    GSPMD-auto 1F1B engine."""
    _parity("zbh1", sp)


def test_zbvpp_tp2_sp_matches_gspmd_1f1b():
    """ZB-V with tp=2 + sequence parallel — the two-lane schedule whose
    in-tick phase races motivated the serialize_phases barriers."""
    _parity("zbvpp", True)


def test_zbh1_tp2_dp2_hybrid_composes():
    """dp2 x pp2 x tp2 (8 devices): the dp gradient psum sits outside
    the manual {'pp','tp'} region and must still compose."""
    _parity("zbh1", True, dp=2)


def test_manual_tp_guards():
    """Divisibility + platform guards fail fast with diagnoses."""
    from paddle_tpu.models.gpt_manual_tp import train_grads_zb_manual_tp
    pcfg = GH.ParallelConfig(dp=1, tp=2, pp=2, microbatches=2,
                             pp_schedule="zbh1")
    bad_heads = GPTConfig(vocab_size=64, hidden_size=30, num_layers=4,
                          num_heads=3, max_seq_len=32)
    ids = jnp.zeros((2, 8), jnp.int32)
    with pytest.raises(ValueError, match="num_heads"):
        train_grads_zb_manual_tp(None, (ids, ids), bad_heads, pcfg,
                                 None)
    # XLA:CPU needs the sequential thunk scheduler (conftest sets it);
    # without the flag the build must refuse with the diagnosis rather
    # than deadlock 40s into the first step
    old = os.environ.get("XLA_FLAGS", "")
    try:
        os.environ["XLA_FLAGS"] = old.replace(
            "--xla_cpu_enable_concurrency_optimized_scheduler=false",
            "")
        with pytest.raises(RuntimeError, match="concurrency"):
            train_grads_zb_manual_tp(None, (ids, ids), CFG, pcfg, None)
    finally:
        os.environ["XLA_FLAGS"] = old


def test_zbh1_tp2_nondivisible_vocab_pads():
    """vocab_size % tp != 0 (the GPT-2 50257 shape class): the manual
    head pads wte to a tp multiple with -inf-masked rows — same loss
    and grads as the GSPMD oracle, zero grads for rows that do not
    exist. Keeps planner-driven zero_bubble configs runnable for any
    vocab (round-5 review finding)."""
    cfg63 = GPTConfig(vocab_size=63, hidden_size=32, num_layers=4,
                      num_heads=4, max_seq_len=32, ffn_mult=2)
    _parity("zbh1", False, cfg=cfg63)


def test_collective_matmul_under_pp_via_manual_tp():
    """The round-4 'cm under pp>1' hole, closed for the LOCKSTEP 1F1B
    route: ring collective matmuls (sp_*_matmul_local) inside the
    manual-tp stage body — tp manual at the same level as pp, no
    nested region, so the Shardy wall (benchmarks/probes/_cm_repro.py) does
    not apply. The cond-gated zero-bubble schedules cannot host the
    ring (ppermute lowers to a whole-mesh op; idle stages never
    arrive — probe leg E) and must refuse it with a diagnosis."""
    _parity("1f1b", True, cm=True)
    with pytest.raises(ValueError, match="collective_matmul"):
        GH._validate_pp_schedule(GH.ParallelConfig(
            dp=1, tp=2, pp=2, sp=True, microbatches=4,
            pp_schedule="zbh1", collective_matmul=True))
    # planner precedence: zero_bubble wins, the ring is dropped
    from paddle_tpu.distributed.planner import PlanCandidate
    pc = PlanCandidate(dp=1, tp=2, pp=2, sp=True, microbatches=4)
    cfgzb = pc.to_parallel_config(zero_bubble=True)
    assert cfgzb.pp_schedule == "zbh1" and not cfgzb.collective_matmul


@pytest.mark.parametrize("sched", ["zbh1", "zbvpp"])
def test_zero_bubble_moe_manual_ep_matches_gspmd(sched):
    """Zero-bubble x EP-MoE (round 5, the last schedule composition):
    the manual-ep stage body — explicit all_to_all over the manual dp
    axis inside the cond-gated phases (probe leg F) — matches the
    GSPMD 1F1B MoE engine's loss and grads exactly."""
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                    num_heads=4, max_seq_len=32, ffn_mult=2)
    pk = dict(dp=2, tp=1, pp=2, sp=False, microbatches=4,
              num_experts=4, param_dtype=jnp.float32,
              compute_dtype=jnp.float32, fused_ce=False, remat=True)
    ids = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)

    def run(pcfg):
        mesh = GH.build_mesh(pcfg)
        params = GH.init_params(cfg, pcfg, jax.random.PRNGKey(0))
        params, _ = GH.shard_params(params, mesh, cfg, pcfg)
        with mesh:
            loss, grads = jax.jit(
                lambda p, b: GH._train_grads_1f1b(p, b, cfg, pcfg,
                                                  mesh))(
                    params, (ids, ids))
            loss.block_until_ready()
        return float(loss), {
            **_flat_blocks(grads, pcfg, cfg),
            "wte": np.asarray(grads["wte"]),
            "lnf_g": np.asarray(grads["lnf_g"]),
        }

    rl, rg = run(GH.ParallelConfig(pp_schedule="1f1b", **pk))
    zl, zg = run(GH.ParallelConfig(pp_schedule=sched, **pk))
    np.testing.assert_allclose(zl, rl, rtol=2e-5)
    for k in rg:
        np.testing.assert_allclose(zg[k], rg[k], rtol=3e-4, atol=3e-5,
                                   err_msg=k)
