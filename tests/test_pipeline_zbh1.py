"""Compiled zero-bubble ZBH1 (VERDICT r3 item 3; reference
pipeline_zero_bubble.py:62): dx/dW-split backward on the 1F1B ring with
cond-gated phases and deferred weight-grads.

Covers: numerical parity with compiled 1F1B (same grads, any split),
schedule-equivalence of the compiled timeline against the dependency
simulator, bubble <= the fused compiled 1F1B at pp=4/M=8, and the
engine wiring (pp_schedule='zbh1' trains with loss parity)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.parallel.pipeline_1f1b import (
    compiled_1f1b_schedule, compiled_zbh1_schedule, pipeline_train_1f1b,
    pipeline_train_zbh1, zbh1_extra_ticks)


def _run(pipeline_fn, n, m, seed=0, hidden=8):
    """Tiny linear-stage pipeline on an n-device mesh; returns
    (loss, grads, head_grads, dx0)."""
    mesh = Mesh(np.array(jax.devices()[:n]), ("pp",))
    rng = np.random.RandomState(seed)
    W = jnp.asarray(rng.randn(n, hidden, hidden).astype(np.float32))
    xs = jnp.asarray(rng.randn(m, 2, hidden).astype(np.float32))
    tgt = jnp.asarray(rng.randn(m, 2, hidden).astype(np.float32))
    hw = jnp.asarray(rng.randn(hidden, hidden).astype(np.float32))

    def stage_fn(w, x):
        return jnp.tanh(x @ w)

    def last_grad(y, hp, mb):
        def head_loss(hp_, y_):
            return jnp.mean((y_ @ hp_ - tgt[mb]) ** 2) / m
        l, (ghp, gy) = jax.value_and_grad(
            head_loss, argnums=(0, 1))(hp, y)
        return l, gy, ghp

    from jax import shard_map
    with mesh:
        out = shard_map(
            lambda W_, xs_, hw_: pipeline_fn(
                stage_fn, W_, xs_, last_grad, head_params=hw_),
            mesh=mesh, axis_names={"pp"},
            in_specs=(P("pp"), P(None), P(None)),
            out_specs=(P(), P("pp"), P(), P(None)))(W, xs, hw)
    return out


@pytest.mark.parametrize("n,m", [(2, 4), (4, 8)])
def test_zbh1_grads_match_1f1b(n, m):
    loss1, g1, h1, d1 = _run(pipeline_train_1f1b, n, m)
    loss2, g2, h2, d2 = _run(pipeline_train_zbh1, n, m)
    np.testing.assert_allclose(np.asarray(loss1), np.asarray(loss2),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h2),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                               rtol=1e-5, atol=1e-6)


def test_compiled_timeline_is_valid_and_complete():
    """Schedule equivalence: the exact compiled timeline simulates
    without deadlock and contains every F/B/W cell exactly once."""
    for n, m in [(2, 4), (4, 8), (4, 4), (3, 6)]:
        sched = compiled_zbh1_schedule(n, m)
        makespan, bubble = sched.simulate()   # raises if invalid
        for s in range(n):
            for kind in "FBW":
                mbs = sorted(op.mb for op in sched.per_stage[s]
                             if op.kind == kind)
                assert mbs == list(range(m)), (s, kind, mbs)


def test_zbh1_bubble_not_worse_than_fused_1f1b():
    """The done-bar measurement: at pp=4/M=8, the cond-gated ZBH1
    timeline's bubble fraction is below the lockstep fused 1F1B's,
    whose every tick costs the full F+fused-B regardless of masking
    (durations F=1, B=3: stage-recompute + dx + dW)."""
    n, m = 4, 8
    zb = compiled_zbh1_schedule(n, m)
    zb_makespan, zb_bubble = zb.simulate()
    # lockstep fused 1F1B: T ticks, each full cost
    t_1f1b = (m + 2 * (n - 1)) * 4.0
    work_1f1b = m * 4.0
    bubble_1f1b = 1.0 - work_1f1b / t_1f1b
    assert zb_bubble < bubble_1f1b, (zb_bubble, bubble_1f1b)
    # and ZBH1's wall-clock proxy (makespan) also beats lockstep 1F1B
    # despite the +1 recompute unit per microbatch
    assert zb_makespan < t_1f1b, (zb_makespan, t_1f1b)


def test_extra_ticks_drain_backlog():
    # small-M configs defer W's past the grid; the drain count must
    # cover the worst stage
    for n, m in [(2, 2), (4, 4), (4, 8), (3, 3)]:
        e = zbh1_extra_ticks(n, m)
        t_grid = m + 2 * (n - 1)
        sched = compiled_zbh1_schedule(n, m)
        assert e >= 0
        # every W present even when deferred past the grid
        for s in range(n):
            assert sum(1 for op in sched.per_stage[s]
                       if op.kind == "W") == m


def test_engine_zbh1_loss_parity():
    """pp_schedule='zbh1' through the hybrid engine: same loss curve
    as 1f1b and as the single-device run."""
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                    num_heads=2, max_seq_len=32)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (4, 32)))

    losses = {}
    for sched in ("1f1b", "zbh1"):
        pcfg = GH.ParallelConfig(dp=1, pp=2, tp=1, microbatches=2,
                                 pp_schedule=sched, remat=True)
        mesh, params, opt, step = GH.setup(cfg, pcfg, seed=0,
                                           devices=jax.devices()[:2])
        with mesh:
            curve = []
            for _ in range(4):
                params, opt, loss = step(params, opt, (ids, ids))
                curve.append(float(loss))
        losses[sched] = curve
    np.testing.assert_allclose(losses["1f1b"], losses["zbh1"],
                               rtol=2e-5)


def test_zbh1_schedule_composition_guards():
    """Since round 5, tp>1 AND ep-MoE each compose with the
    zero-bubble schedules (manual-tp / manual-ep stage bodies,
    models/gpt_manual_tp.py); only their COMBINATION is refused."""
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                    num_heads=2, max_seq_len=16)
    # tp>1 AND EP-MoE together: rejected (no combined manual body)
    pcfg_moe = GH.ParallelConfig(dp=2, pp=2, tp=2, microbatches=2,
                                 num_experts=2, pp_schedule="zbh1")
    with pytest.raises(ValueError, match="MoE"):
        GH.build_train_step(cfg, pcfg_moe, None)
    # each alone: accepted
    GH._validate_pp_schedule(GH.ParallelConfig(
        dp=2, pp=2, tp=1, microbatches=2, num_experts=2,
        pp_schedule="zbh1"))
    # tp>1: accepted — validation passes (full parity is covered by
    # tests/test_pipeline_zb_tp.py)
    pcfg_tp = GH.ParallelConfig(dp=1, pp=2, tp=2, microbatches=2,
                                pp_schedule="zbh1")
    GH._validate_pp_schedule(pcfg_tp)
