"""Compiled zero-bubble ZB-V / ZBVPP (VERDICT r3 item 3, second half;
reference pipeline_zero_bubble.py:151): two V-placed model chunks per
device with dx/dW-split cond-gated backward, in ONE XLA program.

Covers: numerical parity against a plain sequential autodiff oracle
(loss, per-virtual-stage grads in V layout, head grads, input
cotangents), schedule-equivalence of the compiled timeline against the
dependency simulator (chunk_dirs=[1,-1]), bubble/makespan below the
lockstep fused interleaved-VPP accounting, drain coverage of the W
backlog, engine wiring (pp_schedule='zbvpp' loss parity with 1f1b and
eval relayout parity), and the collective-free-stage guard."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.parallel.pipeline_1f1b import (
    compiled_zbvpp_schedule, pipeline_train_zbvpp, zbvpp_extra_ticks)


def _run_zbv(n, m, seed=0, hidden=8):
    """Tiny tanh-stage V pipeline on an n-device mesh vs a sequential
    oracle over the same 2n virtual stages. Returns (got, want) where
    each is (loss, per-vstage grads in V layout, head grads, dx0)."""
    ng = 2 * n
    mesh = Mesh(np.array(jax.devices()[:n]), ("pp",))
    rng = np.random.RandomState(seed)
    Wv = jnp.asarray(rng.randn(ng, hidden, hidden).astype(np.float32))
    xs = jnp.asarray(rng.randn(m, 2, hidden).astype(np.float32))
    tgt = jnp.asarray(rng.randn(m, 2, hidden).astype(np.float32))
    hw = jnp.asarray(rng.randn(hidden, hidden).astype(np.float32))

    def stage_fn(w, x):
        return jnp.tanh(x @ w)

    def last_grad(y, hp, mb):
        def head_loss(hp_, y_):
            return jnp.mean((y_ @ hp_ - tgt[mb]) ** 2) / m
        l, (ghp, gy) = jax.value_and_grad(
            head_loss, argnums=(0, 1))(hp, y)
        return l, gy, ghp

    # V placement: device s holds [W[s], W[2n-1-s]]
    vidx = np.stack([np.arange(n), ng - 1 - np.arange(n)], axis=1)
    Wz = Wv[vidx]                                   # [n, 2, h, h]
    with mesh:
        loss, grads, hgrads, dx0 = shard_map(
            lambda W_, xs_, hw_: pipeline_train_zbvpp(
                stage_fn, W_, xs_, last_grad, head_params=hw_),
            mesh=mesh, axis_names={"pp"},
            in_specs=(P("pp"), P(None), P(None)),
            out_specs=(P(), P("pp"), P(), P(None)))(Wz, xs, hw)

    def ref_loss(Wv_, hw_, xs_):
        total = 0.0
        for i in range(m):
            h = xs_[i]
            for sig in range(ng):
                h = jnp.tanh(h @ Wv_[sig])
            total = total + jnp.mean((h @ hw_ - tgt[i]) ** 2) / m
        return total

    rl, (rgW, rghw, rgx) = jax.value_and_grad(
        ref_loss, argnums=(0, 1, 2))(Wv, hw, xs)
    return (loss, np.asarray(grads), np.asarray(hgrads),
            np.asarray(dx0)), (rl, np.asarray(rgW), np.asarray(rghw),
                               np.asarray(rgx))


@pytest.mark.parametrize("n,m", [(2, 4), (4, 8)])
def test_zbvpp_grads_match_sequential_oracle(n, m):
    (loss, gz, hg, d0), (rl, rgW, rghw, rgx) = _run_zbv(n, m)
    ng = 2 * n
    np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
    for s in range(n):
        np.testing.assert_allclose(gz[s, 0], rgW[s],
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(gz[s, 1], rgW[ng - 1 - s],
                                   rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(hg, rghw, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(d0, rgx, rtol=1e-4, atol=1e-5)


def test_compiled_zbv_timeline_is_valid_and_complete():
    """Schedule equivalence: the exact compiled timeline simulates
    without deadlock under the V-placement dependency chain
    (chunk_dirs=[1,-1]) and contains every per-chunk F/B/W cell exactly
    once."""
    for n, m in [(2, 4), (4, 8), (4, 4), (3, 6)]:
        sched = compiled_zbvpp_schedule(n, m)
        assert sched.chunk_dirs == [1, -1]
        sched.simulate()                    # raises if invalid
        for s in range(n):
            for kind in "FBW":
                for c in (0, 1):
                    mbs = sorted(op.mb for op in sched.per_stage[s]
                                 if op.kind == kind and op.chunk == c)
                    assert mbs == list(range(m)), (s, kind, c, mbs)


def test_zbvpp_bubble_beats_lockstep_interleaved():
    """The cond-gated ZB-V timeline's bubble and makespan are below the
    lockstep fused interleaved-VPP(v=2) accounting, whose every tick
    costs both lanes' full F + fused-B (durations F=1, fused B=3)
    regardless of masking."""
    n, m = 4, 8
    ng = 2 * n
    zb = compiled_zbvpp_schedule(n, m)
    mk, bubble = zb.simulate()
    t_lockstep = (m + 2 * (ng - 1)) * 8.0      # 2F + 2 fused-B per tick
    bubble_lockstep = 1.0 - (m * 8.0) / t_lockstep
    assert bubble < bubble_lockstep, (bubble, bubble_lockstep)
    assert mk < t_lockstep, (mk, t_lockstep)


def test_zbv_extra_ticks_drain_backlog():
    for n, m in [(2, 2), (2, 4), (4, 4), (3, 3)]:
        e = zbvpp_extra_ticks(n, m)
        assert e >= 0
        sched = compiled_zbvpp_schedule(n, m)
        for s in range(n):
            assert sum(1 for op in sched.per_stage[s]
                       if op.kind == "W") == 2 * m


def test_engine_zbvpp_loss_parity():
    """pp_schedule='zbvpp' through the hybrid engine: same loss curve
    as 1f1b (which itself matches single-device)."""
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                    num_heads=2, max_seq_len=32)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (4, 32)))

    losses = {}
    for sched in ("1f1b", "zbvpp"):
        pcfg = GH.ParallelConfig(dp=1, pp=2, tp=1, microbatches=2,
                                 pp_schedule=sched, remat=True)
        mesh, params, opt, step = GH.setup(cfg, pcfg, seed=0,
                                           devices=jax.devices()[:2])
        with mesh:
            curve = []
            for _ in range(4):
                params, opt, loss = step(params, opt, (ids, ids))
                curve.append(float(loss))
        losses[sched] = curve
    np.testing.assert_allclose(losses["1f1b"], losses["zbvpp"],
                               rtol=2e-5)


def test_engine_zbvpp_eval_relayout_parity():
    """forward_hidden under the ZB-V [pp, 2, Lc] stacking matches the
    pp=1 forward on identical weights (the eval relayout gathers the
    virtual stages back into layer order)."""
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                    num_heads=2, max_seq_len=32)
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 128, (2, 32)))

    pcfg1 = GH.ParallelConfig(dp=1, pp=1, tp=1, remat=False,
                              param_dtype=jnp.float32,
                              compute_dtype=jnp.float32)
    mesh1 = GH.build_mesh(pcfg1, jax.devices()[:1])
    params = GH.init_params(cfg, pcfg1, jax.random.PRNGKey(0))
    with mesh1:
        want = np.asarray(GH.forward_hidden(params, ids, cfg, pcfg1,
                                            mesh1))

    # f32 end-to-end: XLA:CPU's AllReducePromotion CHECK-crashes on the
    # bf16 psum this eval path would otherwise emit (NOTES gotcha)
    pcfgv = GH.ParallelConfig(dp=1, pp=2, tp=1, microbatches=2,
                              pp_schedule="zbvpp", remat=False,
                              param_dtype=jnp.float32,
                              compute_dtype=jnp.float32)
    meshv = GH.build_mesh(pcfgv, jax.devices()[:2])
    paramsv, _ = GH.shard_params(
        jax.tree_util.tree_map(lambda x: x, params), meshv, cfg, pcfgv)
    with meshv:
        got = np.asarray(GH.forward_hidden(paramsv, ids, cfg, pcfgv,
                                           meshv))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_zbvpp_rejects_collective_stage_bodies_and_bad_layers():
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models import gpt_hybrid as GH
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=4,
                    num_heads=2, max_seq_len=16)
    # tp>1 AND ep-MoE each compose since round 5 (manual-tp /
    # manual-ep stage bodies); only their COMBINATION is refused
    pcfg = GH.ParallelConfig(dp=2, pp=2, tp=2, microbatches=2,
                             num_experts=2, pp_schedule="zbvpp")
    with pytest.raises(ValueError, match="MoE"):
        GH.build_train_step(cfg, pcfg, None)
    GH._validate_pp_schedule(GH.ParallelConfig(
        dp=1, pp=2, tp=2, microbatches=2, pp_schedule="zbvpp"))
    GH._validate_pp_schedule(GH.ParallelConfig(
        dp=2, pp=2, tp=1, microbatches=2, num_experts=2,
        pp_schedule="zbvpp"))
    # pp=1 has no ring for the V placement
    with pytest.raises(ValueError, match="pp > 1"):
        GH.build_train_step(
            cfg, GH.ParallelConfig(dp=1, pp=1, pp_schedule="zbvpp"),
            None)
    # layers must split 2*pp ways
    cfg6 = GPTConfig(vocab_size=64, hidden_size=32, num_layers=6,
                     num_heads=2, max_seq_len=16)
    pcfg6 = GH.ParallelConfig(dp=1, pp=2, tp=1, microbatches=2,
                              pp_schedule="zbvpp")
    mesh = GH.build_mesh(pcfg6, jax.devices()[:2])
    params = GH.init_params(cfg6, pcfg6, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="2\\*pp"):
        GH.shard_params(params, mesh, cfg6, pcfg6)
