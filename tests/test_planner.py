"""Parallel-plan search (distributed/planner.py) — reference:
auto_parallel/static/planner_v2.py over the static_op_benchmark table.

Acceptance (VERDICT round-1 item 7): the planner's cost model is
calibrated against the repo's own recorded v5e bench points, reproduces
the hand-found configs for the BASELINE workloads, and its top-1 plan
executes on the 8-virtual-device mesh.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.distributed.planner import ModelSpec, Planner, PlanCandidate

GPT13 = ModelSpec.gpt(1.3e9, layers=24, hidden=2048, heads=16,
                      seq=1024, vocab=50257)
LLAMA7 = ModelSpec.gpt(6.7e9, layers=32, hidden=4096, heads=32,
                       seq=2048, vocab=32000)


def test_calibration_against_recorded_bench():
    """Single-chip GPT-1.3B: the calibrated model must land near the
    driver-recorded 14.57k tok/s/chip, keep B4 feasible and reject B8
    (the measured OOM boundary), and force remat on."""
    p = Planner("v5e")
    plans = p.plan(GPT13, 1, global_batch=4)
    best = plans[0]
    pred = p.throughput(best, GPT13, 4, 1)
    assert 0.7 * 14_570 <= pred <= 1.3 * 14_570, pred
    assert best.remat            # noremat cannot fit 16G at 1.3B
    with pytest.raises(RuntimeError):
        p.plan(GPT13, 1, global_batch=8)


def test_1p3b_8chip_reproduces_hand_config():
    """BASELINE workload 'GPT-3 1.3B DP+sharding-1': at a real global
    batch the planner's top plan is pure data parallel with optimizer
    sharding."""
    p = Planner("v5e")
    best = p.plan(GPT13, 8, global_batch=256)[0]
    assert (best.dp, best.tp, best.pp) == (8, 1, 1), best.short()
    assert best.zero >= 1, best.short()


def test_7b_8chip_needs_model_parallelism():
    """BASELINE workload 'Llama-2 7B TP4xPP2xsharding-3': 7B does not
    fit 16G chips data-parallel-only without ZeRO-3; the planner must
    pick model-parallel sharding, and the hand config's tp>=2 x pp>=2
    family must rank in the top 5."""
    p = Planner("v5e")
    plans = p.plan(LLAMA7, 8, global_batch=32)
    best = plans[0]
    assert best.tp > 1 or best.pp > 1 or best.zero == 3, best.short()
    assert any(c.tp >= 2 and c.pp >= 2 for c in plans), \
        [c.short() for c in plans]
    # pure dp8 without ZeRO-3 is memory-infeasible for 6.7B on 16G
    infeasible = [c for c in plans
                  if (c.dp, c.tp, c.pp, c.zero) == (8, 1, 1, 0)]
    assert not infeasible


def test_7b_engine_capable_reproduces_tp4_pp2():
    """Constrained to the ZeRO stages the compiled engine executes
    (<=1), the planner's TOP-1 for 7B on 8 v5e chips is the BASELINE
    hand config itself: TP4 x PP2 (+sp). Since round 3 this plan family
    is EXECUTABLE by the generic auto-parallel Engine on any model with
    a homogeneous block chain (partitioner.py imposes tp via mp-axis
    annotation and pp via the compiled 1F1B) — the bespoke hybrid
    engine remains the tuned perf path, not the only capable one
    (tests/test_auto_engine.py::test_engine_tp_pp_on_stock_llama_
    loss_parity)."""
    p = Planner("v5e", zero_stages=(0, 1))
    best = p.plan(LLAMA7, 8, global_batch=32)[0]
    assert (best.tp, best.pp) == (4, 2), best.short()
    assert best.sp


def test_larger_meshes_plan():
    p = Planner("v5p")
    for n in (16, 32):
        plans = p.plan(LLAMA7, n, global_batch=256)
        best = plans[0]
        assert best.dp * best.tp * best.pp == n
        # 95G chips: dp-major with optimizer sharding wins at scale
        assert best.dp >= n // 4, best.short()


def test_infeasible_raises():
    p = Planner("v5e")
    with pytest.raises(RuntimeError, match="no feasible"):
        p.plan(ModelSpec.gpt(70e9, 80, 8192, 64, 4096, 32000), 1, 8)


def test_breakdown_and_tie_break():
    p = Planner("v5e")
    plans = p.plan(GPT13, 8, global_batch=256)
    for c in plans:
        assert c.est_step_s > 0 and "compute" in c.breakdown
    # among near-equal-time dp8 plans, lower-memory zero stages first
    dp8 = [c for c in plans if (c.dp, c.tp, c.pp) == (8, 1, 1)]
    for a, b in zip(dp8, dp8[1:]):
        assert (round(a.est_step_s, 3), a.est_mem_bytes) <= \
               (round(b.est_step_s, 3), b.est_mem_bytes)


def test_top1_validates_via_dryrun():
    """The planner's chosen config for a small model executes one real
    hybrid train step on the 8-device mesh (the reference planner's
    'plan must run' check)."""
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models.gpt_hybrid import ParallelConfig, setup

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                    num_heads=4, max_seq_len=16)
    spec = ModelSpec.from_config(cfg)
    # zero_stages limited to what the compiled hybrid engine executes
    planner = Planner("v5e", zero_stages=(0, 1))
    best = planner.plan(spec, 8, global_batch=16)[0]
    pcfg = ParallelConfig(
        dp=best.dp, pp=best.pp, tp=best.tp, sp=best.sp,
        zero1=best.zero >= 1,
        microbatches=max(best.microbatches, 1),
        remat=best.remat,
        pp_schedule="1f1b" if best.pp > 1 else "gpipe",
        param_dtype=jnp.float32, compute_dtype=jnp.float32)
    mesh, params, opt, step = setup(cfg, pcfg, seed=0,
                                    devices=jax.devices()[:8])
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (16, 16)))
    with mesh:
        _, _, loss = step(params, opt, (ids, ids))
    assert np.isfinite(float(loss))


def test_model_spec_from_config():
    from paddle_tpu.models.gpt import GPTConfig
    cfg = GPTConfig(vocab_size=50257, hidden_size=2048, num_layers=24,
                    num_heads=16, max_seq_len=1024)
    spec = ModelSpec.from_config(cfg)
    # parameter-count formula lands near the real 1.3B
    assert 1.1e9 < spec.n_params < 1.6e9, spec.n_params


def test_plan_to_parallel_config_carries_collective_matmul():
    from paddle_tpu.distributed.planner import PlanCandidate
    p = PlanCandidate(dp=2, tp=4, pp=1, sp=True, zero=1, microbatches=1)
    pcfg = p.to_parallel_config()
    assert pcfg.collective_matmul and pcfg.zero1 and pcfg.tp == 4
    assert "+cm" in p.short()
    # pp>1: the ring rides the manual-tp route, which has no fused-CE
    # form — with fused_ce on (the default) the memory win outranks
    # the overlap and cm is dropped; fused_ce=False takes the ring
    p2 = PlanCandidate(dp=1, tp=4, pp=2, sp=True, microbatches=4)
    pcfg2 = p2.to_parallel_config(remat=False)
    assert not pcfg2.collective_matmul and pcfg2.fused_ce
    pcfg2r = p2.to_parallel_config(remat=False, fused_ce=False)
    assert pcfg2r.collective_matmul and pcfg2r.pp_schedule == "1f1b"
    assert pcfg2r.remat is False
    # no sp -> no ring
    p3 = PlanCandidate(dp=1, tp=4, pp=2, sp=False, microbatches=4)
    assert not p3.to_parallel_config(
        fused_ce=False).collective_matmul


def test_plan_to_parallel_config_zero_bubble_knob():
    """zero_bubble=True upgrades pp>1 plans to the compiled ZBH1 —
    since round 5 under tp>1 too (manual-tp stage body)."""
    from paddle_tpu.distributed.planner import PlanCandidate
    p = PlanCandidate(dp=2, tp=1, pp=4, microbatches=8)
    assert p.to_parallel_config(zero_bubble=True).pp_schedule == "zbh1"
    assert p.to_parallel_config().pp_schedule == "1f1b"
    p_tp = PlanCandidate(dp=1, tp=2, pp=4, microbatches=8)
    assert p_tp.to_parallel_config(
        zero_bubble=True).pp_schedule == "zbh1"
    p1 = PlanCandidate(dp=8, tp=1, pp=1)
    assert p1.to_parallel_config(
        zero_bubble=True).pp_schedule == "gpipe"
    # the "zbvpp" string selects ZB-V (Engine.prepare's contract);
    # unknown strings raise instead of silently degrading to zbh1
    assert p.to_parallel_config(
        zero_bubble="zbvpp").pp_schedule == "zbvpp"
    import pytest
    with pytest.raises(ValueError, match="zero_bubble"):
        p.to_parallel_config(zero_bubble="zb2p")
