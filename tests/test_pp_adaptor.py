"""PP checkpoint layout conversion (fleet/pp_parallel_adaptor) and the
accuracy_check cross-run comparison op (reference ops.yaml accuracy_check)."""
import numpy as np
import pytest

from paddle_tpu.distributed.fleet.pp_parallel_adaptor import (
    ParallelConfig, PipeLineModelAdaptor, convert_pp_state_dicts)


def _make_stage_dicts(num_layers, cfg, with_shared=True):
    chunks = cfg.stage_chunks(num_layers)
    dicts = []
    for s, layer_ids in enumerate(chunks):
        d = {}
        for local, g in enumerate(layer_ids):
            d[f"layers.{local}.w"] = np.full((2,), float(g))
            d[f"layers.{local}.b"] = np.full((2,), 100.0 + g)
        if with_shared and s == 0:
            d["shared_embed.weight"] = np.arange(4.0)
        dicts.append(d)
    return dicts


def _global_view(stage_dicts, cfg, num_layers):
    """global layer id -> param dict, via the stage chunk map."""
    out = {}
    for d, layer_ids in zip(stage_dicts, cfg.stage_chunks(num_layers)):
        for local, g in enumerate(layer_ids):
            out[g] = {k.split(".", 2)[2]: v for k, v in d.items()
                      if k.startswith(f"layers.{local}.")}
    return out


@pytest.mark.parametrize("src_pp,src_vpp,dst_pp,dst_vpp", [
    (2, 1, 4, 1),     # widen pipeline
    (4, 1, 2, 1),     # narrow pipeline
    (2, 2, 4, 1),     # interleaved VPP -> plain
    (1, 1, 2, 2),     # single stage -> interleaved
])
def test_roundtrip_preserves_global_layers(src_pp, src_vpp, dst_pp,
                                           dst_vpp):
    L = 8
    src = ParallelConfig(src_pp, src_vpp)
    dst = ParallelConfig(dst_pp, dst_vpp)
    stage_dicts = _make_stage_dicts(L, src)
    converted = convert_pp_state_dicts(stage_dicts, src, dst)
    assert len(converted) == dst_pp
    # every global layer's params survive with correct values
    gv = _global_view(converted, dst, L)
    assert sorted(gv) == list(range(L))
    for g in range(L):
        np.testing.assert_array_equal(gv[g]["w"], np.full((2,), float(g)))
        np.testing.assert_array_equal(gv[g]["b"],
                                      np.full((2,), 100.0 + g))
    # shared (non-layer) entries are replicated to all dst stages
    for d in converted:
        np.testing.assert_array_equal(d["shared_embed.weight"],
                                      np.arange(4.0))


def test_vpp_interleaving_order():
    """VPP chunk c of stage s holds layers [(c*pp+s)*per, ...): the
    reference interleaved assignment."""
    cfg = ParallelConfig(pp=2, vpp=2)
    assert cfg.stage_chunks(8) == [[0, 1, 4, 5], [2, 3, 6, 7]]


def test_adaptor_class_api():
    src, dst = ParallelConfig(2), ParallelConfig(4)
    ad = PipeLineModelAdaptor(src, dst)
    out = ad.apply(_make_stage_dicts(8, src, with_shared=False))
    assert len(out) == 4 and all("layers.0.w" in d for d in out)
    assert all(isinstance(s, str) for s in ad.peek_model(
        _make_stage_dicts(8, src, with_shared=False)))


def test_bad_shapes_raise():
    with pytest.raises(ValueError):
        convert_pp_state_dicts([{}, {}], ParallelConfig(3),
                               ParallelConfig(2))
    with pytest.raises(ValueError):
        ParallelConfig(2).stage_chunks(7)


class TestAccuracyCheck:
    def test_pass_and_fail(self):
        import paddle_tpu as paddle
        from paddle_tpu.ops.extra import accuracy_check
        x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        y = paddle.to_tensor(np.array([1.0, 2.0, 3.0 + 1e-7], np.float32))
        out = accuracy_check(x, y, fn_name="matmul")
        assert np.asarray(out.numpy()).all()
        z = paddle.to_tensor(np.array([1.0, 2.0, 4.0], np.float32))
        with pytest.raises(AssertionError, match="matmul"):
            accuracy_check(x, z, fn_name="matmul")

    def test_equal_nan(self):
        import paddle_tpu as paddle
        from paddle_tpu.ops.extra import accuracy_check
        x = paddle.to_tensor(np.array([np.nan, 1.0], np.float32))
        y = paddle.to_tensor(np.array([np.nan, 1.0], np.float32))
        with pytest.raises(AssertionError):
            accuracy_check(x, y)
        assert np.asarray(
            accuracy_check(x, y, equal_nan=True).numpy()).all()

    def test_matching_infs_are_equal(self):
        import paddle_tpu as paddle
        from paddle_tpu.ops.extra import accuracy_check
        x = paddle.to_tensor(np.array([1.0, np.inf, -np.inf], np.float32))
        y = paddle.to_tensor(np.array([1.0, np.inf, -np.inf], np.float32))
        assert np.asarray(accuracy_check(x, y).numpy()).all()
        z = paddle.to_tensor(np.array([1.0, -np.inf, np.inf], np.float32))
        with pytest.raises(AssertionError):
            accuracy_check(x, z)
