"""Pipeline schedule descriptor tests: validity (dependency simulation),
cost properties (zero-bubble < 1F1B makespan; 1F1B < F-then-B memory),
and numerical equivalence of every schedule against direct autodiff —
the reference's loss-parity methodology for its scheduler passes
(test/distributed_passes/, pipeline_scheduler_pass)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.parallel.pp_schedule import (
    PipeOp, Schedule, run_schedule, schedule_1f1b, schedule_fthenb,
    schedule_interleaved, schedule_zbh1, schedule_zbvpp)

N_STAGES, N_MB = 4, 8


def _all_cells_present(sched, with_w):
    kinds = {"F", "B"} | ({"W"} if with_w else set())
    want = {(k, s, m, c)
            for k in kinds for s in range(sched.n_stages)
            for m in range(sched.n_microbatches)
            for c in range(sched.n_chunks)}
    got = {(op.kind, op.stage, op.mb, op.chunk)
           for ops in sched.per_stage for op in ops}
    assert got == want


@pytest.mark.parametrize("maker,with_w", [
    (lambda: schedule_fthenb(N_STAGES, N_MB), False),
    (lambda: schedule_1f1b(N_STAGES, N_MB), False),
    (lambda: schedule_zbh1(N_STAGES, N_MB), True),
    (lambda: schedule_interleaved(N_STAGES, N_MB, 2), False),
    (lambda: schedule_zbvpp(N_STAGES, N_MB), True),
    (lambda: schedule_zbvpp(N_STAGES, N_MB, mem_limit=N_STAGES + 1),
     True),
])
def test_schedule_valid_and_complete(maker, with_w):
    sched = maker()
    _all_cells_present(sched, with_w)
    makespan, bubble = sched.simulate()  # raises on deadlock
    assert makespan > 0 and 0 <= bubble < 1


def test_1f1b_memory_beats_fthenb():
    assert schedule_1f1b(N_STAGES, N_MB).peak_activations() <= N_STAGES
    assert schedule_fthenb(N_STAGES, N_MB).peak_activations() == N_MB


def test_zero_bubble_beats_1f1b_makespan():
    m1, b1 = schedule_1f1b(N_STAGES, N_MB).simulate()
    mz, bz = schedule_zbh1(N_STAGES, N_MB).simulate()
    assert mz < m1
    assert bz < b1
    # ZB-V: same per-virtual-stage work at half stage granularity; its
    # bubble must also undercut the fused-backward 1F1B's (the
    # schedule_zbvpp docstring's claim)
    _, bv = schedule_zbvpp(N_STAGES, N_MB).simulate()
    assert bv < b1


def test_interleaving_reduces_bubble():
    _, b1 = schedule_1f1b(N_STAGES, N_MB).simulate()
    _, bv = schedule_interleaved(N_STAGES, N_MB, 2).simulate()
    assert bv < b1


# ---------------------------------------------------------------------
# Numerical equivalence: a 4-stage (x W_s chain) pipeline must produce
# identical outputs + weight grads under every schedule.
# ---------------------------------------------------------------------

def _problem(n_virtual):
    rng = np.random.RandomState(0)
    ws = [jnp.asarray(rng.randn(8, 8).astype(np.float32) * 0.3)
          for _ in range(n_virtual)]
    xs = [jnp.asarray(rng.randn(2, 8).astype(np.float32))
          for _ in range(N_MB)]
    return ws, xs


def _reference_grads(ws, xs):
    def loss(ws):
        total = 0.0
        for x in xs:
            h = x
            for w in ws:
                h = jnp.tanh(h @ w)
            total = total + h.sum()
        return total
    return jax.grad(loss)(ws)


def _run(sched, ws, xs, split_wgrad):
    wgrads = [jnp.zeros_like(w) for w in ws]
    # virtual depth honoring per-chunk traversal direction (V placement
    # runs chunk 1 reversed: device s holds virtual stage 2n-1-s)
    vidx = sched.virtual_index

    def forward(stage, chunk, x):
        y = jnp.tanh(x @ ws[vidx(stage, chunk)])
        return y, (x, y)

    def backward(stage, chunk, ctx, gy):
        x, y = ctx
        gz = gy * (1 - y * y)
        if not split_wgrad:
            wgrads[vidx(stage, chunk)] += x.T @ gz
        return gz @ ws[vidx(stage, chunk)].T

    def weight_grad(stage, chunk, ctx, gy):
        x, y = ctx
        gz = gy * (1 - y * y)
        wgrads[vidx(stage, chunk)] += x.T @ gz

    outs = run_schedule(sched, forward, backward,
                        weight_grad if split_wgrad else None, xs,
                        [jnp.ones((2, 8), jnp.float32)] * N_MB)
    return outs, wgrads


@pytest.mark.parametrize("maker,split_wgrad,n_virtual", [
    (lambda: schedule_fthenb(N_STAGES, N_MB), False, N_STAGES),
    (lambda: schedule_1f1b(N_STAGES, N_MB), False, N_STAGES),
    (lambda: schedule_zbh1(N_STAGES, N_MB), True, N_STAGES),
    (lambda: schedule_interleaved(N_STAGES, N_MB, 2), False, 2 * N_STAGES),
    (lambda: schedule_zbvpp(N_STAGES, N_MB), True, 2 * N_STAGES),
    (lambda: schedule_zbvpp(N_STAGES, N_MB, mem_limit=N_STAGES + 1),
     True, 2 * N_STAGES),
])
def test_schedule_numerics_match_autodiff(maker, split_wgrad, n_virtual):
    ws, xs = _problem(n_virtual)
    expect = _reference_grads(ws, xs)
    outs, wgrads = _run(maker(), ws, xs, split_wgrad)
    # forward outputs match plain chain
    h = xs[0]
    for w in ws:
        h = jnp.tanh(h @ w)
    np.testing.assert_allclose(np.asarray(outs[0]), np.asarray(h),
                               rtol=1e-5)
    for got, exp in zip(wgrads, expect):
        np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                                   rtol=1e-4, atol=1e-5)


def test_run_schedule_rejects_mismatched_weight_grad():
    ws, xs = _problem(N_STAGES)
    with pytest.raises(ValueError, match="W cells"):
        _run(schedule_1f1b(N_STAGES, N_MB), ws, xs, split_wgrad=True)
    with pytest.raises(ValueError, match="weight_grad"):
        _run(schedule_zbh1(N_STAGES, N_MB), ws, xs, split_wgrad=False)
