"""Parameter-server runtime tests (reference test model:
test/ps/* + distributed fleet PS mode — multi-process there; the tables
and RPC run in-process threads here, which exercises the same
push/pull/shard/geo semantics on one host)."""
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import (
    DenseTable, GeoWorker, PsClient, PsServer, SparseGeoTable,
    SparseTable,
)


@pytest.fixture()
def cluster():
    """Two PS servers + one client, torn down after the test."""
    servers = [PsServer(port=0, num_workers=1).start() for _ in range(2)]
    client = PsClient([f"127.0.0.1:{s.port}" for s in servers])
    yield client
    client.stop_servers()
    client.close()
    for s in servers:
        s.stop()


class TestTables:
    def test_dense_sgd(self):
        t = DenseTable(4, optimizer="sgd", lr=0.5)
        t.set(np.ones(4, np.float32))
        t.push(np.ones(4, np.float32))
        np.testing.assert_allclose(t.pull(), 0.5 * np.ones(4))

    def test_sparse_lazy_init_and_adagrad(self):
        t = SparseTable(3, optimizer="adagrad", lr=0.1)
        rows = t.pull(np.array([5, 9]))
        assert rows.shape == (2, 3) and t.size() == 2
        before = t.pull(np.array([5]))[0].copy()
        t.push(np.array([5]), np.ones((1, 3), np.float32))
        after = t.pull(np.array([5]))[0]
        assert (after < before).all()

    def test_geo_table_applies_deltas(self):
        t = SparseGeoTable(2)
        t.pull(np.array([1]))
        base = t.pull(np.array([1]))[0].copy()
        t.push(np.array([1]), np.full((1, 2), 0.25, np.float32))
        np.testing.assert_allclose(t.pull(np.array([1]))[0], base + 0.25)


class TestClientServer:
    def test_dense_partitioned_across_servers(self, cluster):
        cluster.create_dense_table(0, 10, optimizer="sgd", lr=1.0)
        cluster.set_dense(0, np.arange(10, dtype=np.float32))
        np.testing.assert_allclose(cluster.pull_dense(0, 10),
                                   np.arange(10))
        cluster.push_dense(0, np.ones(10, np.float32))
        np.testing.assert_allclose(cluster.pull_dense(0, 10),
                                   np.arange(10) - 1)

    def test_sparse_sharded_by_hash(self, cluster):
        cluster.create_sparse_table(1, dim=4, optimizer="sgd", lr=0.5,
                                    initializer="zeros")
        keys = np.array([0, 1, 2, 3, 4, 5], np.int64)
        rows = cluster.pull_sparse(1, keys)
        np.testing.assert_allclose(rows, 0)
        grads = np.ones((6, 4), np.float32)
        cluster.push_sparse(1, keys, grads)
        np.testing.assert_allclose(cluster.pull_sparse(1, keys), -0.5)
        assert cluster.sparse_size(1) == 6

    def test_barrier_across_workers(self):
        server = PsServer(port=0, num_workers=2).start()
        eps = [f"127.0.0.1:{server.port}"]
        order = []

        def worker(i):
            c = PsClient(eps)
            order.append(("enter", i))
            c.barrier()
            order.append(("exit", i))
            c.close()

        ts = [threading.Thread(target=worker, args=(i,)) for i in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert [e for e, _ in order[:2]] == ["enter", "enter"]
        assert [e for e, _ in order[2:]] == ["exit", "exit"]
        server.stop()


class TestGeoWorker:
    def test_geo_sync_propagates_deltas(self, cluster):
        cluster.create_sparse_table(2, dim=2, geo=True,
                                    initializer="zeros")
        w = GeoWorker(cluster, table_id=2, dim=2, push_interval=2)
        keys = np.array([7], np.int64)
        w.lookup(keys)
        w.apply_grads(keys, np.ones((1, 2), np.float32), lr=0.1)
        # not yet synced (interval=2): server still at 0
        np.testing.assert_allclose(cluster.pull_sparse(2, keys), 0)
        w.apply_grads(keys, np.ones((1, 2), np.float32), lr=0.1)
        # synced: server saw the -0.2 delta
        np.testing.assert_allclose(cluster.pull_sparse(2, keys), -0.2,
                                   rtol=1e-6)


class TestFleetPsMode:
    def test_role_maker_and_fleet_ps_flow(self, monkeypatch):
        from paddle_tpu.distributed import fleet
        server = PsServer(port=0, num_workers=1).start()
        monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
        monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST",
                           f"127.0.0.1:{server.port}")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
        role = fleet.PaddleCloudRoleMaker()
        assert role.is_worker() and not role.is_server()
        fleet.init(role, is_collective=False)
        assert fleet.is_worker()
        fleet.init_worker()
        fleet._fleet.ps_client.create_dense_table(0, 4)
        out = fleet._fleet.ps_client.pull_dense(0, 4)
        assert out.shape == (4,)
        fleet.stop_worker()
        server.stop()

    def test_embedding_lookup_via_ps_feeds_tpu_step(self, cluster):
        """The PS sparse path feeding a device step: pull rows, run a
        jitted dense step, push grads back."""
        cluster.create_sparse_table(3, dim=8, optimizer="sgd", lr=0.1,
                                    initializer="uniform")
        ids = np.array([11, 3, 11, 42], np.int64)
        rows = cluster.pull_sparse(3, ids)
        x = paddle.to_tensor(rows, stop_gradient=False)
        loss = (x * x).sum()
        loss.backward()
        cluster.push_sparse(3, ids, x.grad.numpy())
        # pushed grad = 2*rows with lr 0.1 -> rows shrink toward 0.
        # id 11 appears twice -> gets two updates
        after = cluster.pull_sparse(3, ids)
        assert (np.abs(after) <= np.abs(rows) + 1e-7).all()
        assert cluster.sparse_size(3) == 3


class TestEndToEndPsPipeline:
    def test_datafeed_to_ps_to_device_step(self, cluster, tmp_path):
        """The full PS-mode loop (reference PS CTR flow): MultiSlot
        file -> InMemoryDataset slot arrays -> sparse rows pulled from
        the PS -> dense compute on device -> grads pushed back."""
        import paddle_tpu as paddle
        import paddle_tpu.distributed as dist

        p = tmp_path / "part-0"
        p.write_text("2 4 5 1 1\n1 9 1 0\n2 4 9 1 1\n1 5 1 0\n")
        ds = dist.InMemoryDataset()
        ds.set_filelist([str(p)])

        class V:
            def __init__(self, dtype):
                self.dtype = dtype
        ds.set_use_var([V("int64"), V("float32")])
        ds.load_into_memory()

        dim = 4
        cluster.create_sparse_table(9, dim=dim, optimizer="sgd", lr=0.5,
                                    initializer="uniform")
        losses = []
        for ids_t, label_t in ds.batch_generator(batch_size=2):
            ids = ids_t.numpy().ravel()
            rows = cluster.pull_sparse(9, ids)
            emb = paddle.to_tensor(
                rows.reshape(ids_t.numpy().shape + (dim,)).mean(1),
                stop_gradient=False)
            label = paddle.to_tensor(label_t.numpy().ravel())
            logit = emb.sum(-1)
            loss = ((logit - label) ** 2).mean()
            loss.backward()
            g = emb.grad.numpy() / ids_t.numpy().shape[1]
            grows = np.repeat(g, ids_t.numpy().shape[1], axis=0)
            cluster.push_sparse(9, ids, grows)
            losses.append(float(loss.numpy()))
        # re-run the same data: server-side updates reduced the loss
        relosses = []
        for ids_t, label_t in ds.batch_generator(batch_size=2):
            ids = ids_t.numpy().ravel()
            rows = cluster.pull_sparse(9, ids)
            emb = rows.reshape(ids_t.numpy().shape + (dim,)).mean(1)
            logit = emb.sum(-1)
            relosses.append(float(((logit - label_t.numpy().ravel())
                                   ** 2).mean()))
        assert sum(relosses) < sum(losses), (relosses, losses)
