"""Quantization framework (reference: python/paddle/quantization —
QuantConfig priority resolution, quanter factories, QAT insertion over
layer graphs, PTQ calibrate->convert)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.quantization import (AbsmaxObserver, EMAObserver,
                                     FakeQuanterChannelWiseAbsMax,
                                     FakeQuanterWithAbsMax,
                                     GroupWiseWeightObserver, PTQ, QAT,
                                     QuantConfig, QuantedConv2D,
                                     QuantedLinear, quanter)

rng = np.random.RandomState(5)


def _mlp():
    paddle.seed(9)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def _cnn():
    paddle.seed(9)
    return nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
                         nn.Conv2D(8, 4, 3, padding=1))


def test_qat_default_wraps_linear_and_conv():
    q = QAT(QuantConfig())
    mlp = q.quantize(_mlp())
    kinds = [type(l) for l in mlp]
    assert kinds[0] is QuantedLinear and kinds[2] is QuantedLinear
    cnn = q.quantize(_cnn())
    assert isinstance(cnn[0], QuantedConv2D)
    assert isinstance(cnn[2], QuantedConv2D)


def test_config_priority_instance_over_name_over_type():
    model = _mlp()
    cfg = QuantConfig(activation=None, weight=None)
    cfg.add_type_config(nn.Linear, bit_length=8)
    cfg.add_name_config("2", bit_length=4)          # second Linear
    cfg.add_layer_config(model[0], bit_length=2)    # first Linear
    q = QAT(cfg)
    out = q.quantize(model, inplace=True)
    assert out[0].act_quanter.bit_length == 2       # instance wins
    assert out[2].act_quanter.bit_length == 4       # name beats type


def test_quanter_factory_and_custom_mapping():
    class MyQuanted(QuantedLinear):
        pass

    cfg = QuantConfig(
        activation=quanter(FakeQuanterWithAbsMax, bit_length=4),
        weight=quanter(FakeQuanterChannelWiseAbsMax, bit_length=8))
    cfg.add_qat_layer_mapping(nn.Linear, MyQuanted)
    out = QAT(cfg).quantize(_mlp())
    assert isinstance(out[0], MyQuanted)
    assert out[0].act_quanter.bit_length == 4
    assert isinstance(out[0].weight_quanter,
                      FakeQuanterChannelWiseAbsMax)


def test_qat_trains_and_stays_close():
    model = _mlp()
    x = rng.randn(16, 8).astype(np.float32)
    ref = model(paddle.to_tensor(x)).numpy()
    qmodel = QAT(QuantConfig()).quantize(model)
    opt = paddle.optimizer.SGD(learning_rate=1e-2,
                               parameters=qmodel.parameters())
    y = paddle.to_tensor(rng.randn(16, 4).astype(np.float32))
    losses = []
    for _ in range(10):
        out = qmodel(paddle.to_tensor(x))
        loss = ((out - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss._data))
    assert losses[-1] < losses[0]       # STE gradients flow
    # 8-bit fake-quant forward stays close to fp32 before training
    out0 = QAT(QuantConfig()).quantize(_mlp())(
        paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out0, ref, rtol=0.1, atol=0.1)


def test_channelwise_weight_quanter_smaller_error():
    w = paddle.to_tensor(
        (rng.randn(8, 16) * np.logspace(-2, 0, 16)).astype(np.float32))
    per_tensor = FakeQuanterWithAbsMax()
    per_tensor.eval()
    # seed the per-tensor scale as PTQ would
    per_tensor._scale._assign_array(
        np.abs(w.numpy()).max(keepdims=True).reshape(1) / 127)
    pc = FakeQuanterChannelWiseAbsMax()
    err_t = np.abs(per_tensor(w).numpy() - w.numpy()).mean()
    err_c = np.abs(pc(w).numpy() - w.numpy()).mean()
    assert err_c < err_t                # per-channel strictly better


def test_ptq_calibrate_convert():
    model = _mlp()
    x = rng.randn(32, 8).astype(np.float32)
    ref = model(paddle.to_tensor(x)).numpy()
    ptq = PTQ(QuantConfig())
    model = ptq.quantize(model, inplace=True)
    for i in range(4):                  # calibration passes
        model(paddle.to_tensor(x + 0.01 * i))
    converted = ptq.convert(model)
    out = converted(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, rtol=0.15, atol=0.1)
    # scales were frozen from the observers, not ones
    assert float(converted[0].act_quanter._scale.numpy()[0]) != 1.0
    # original left unquantized with inplace=False convert
    assert isinstance(model[0], nn.Linear)


def test_observers():
    o = AbsmaxObserver()
    o.observe(paddle.to_tensor([1.0, -3.0]))
    o.observe(paddle.to_tensor([2.0]))
    assert abs(o.scale() - 3.0 / 127) < 1e-6
    e = EMAObserver(moving_rate=0.5)
    e.observe(paddle.to_tensor([2.0]))
    e.observe(paddle.to_tensor([4.0]))
    assert abs(e.scale() - 3.0 / 127) < 1e-6
    g = GroupWiseWeightObserver(channel_axis=-1)
    g.observe(paddle.to_tensor(np.array([[1.0, -8.0], [2.0, 4.0]],
                                        np.float32)))
    np.testing.assert_allclose(g.scale(), [2.0 / 127, 8.0 / 127])
