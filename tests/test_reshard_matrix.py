"""Per-transform reshard tests mirroring the reference suite
(test/auto_parallel/reshard_{r_to_s,s_to_r,s_to_s,p_to_r,nd_mesh,
*_cross_mesh}.py): each placement transition must preserve values and
land on the expected sharding. Runs on the 8-device CPU mesh (the
reference's gloo fake-cluster trick, SURVEY §4.2)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist


def _mesh(shape, names):
    return dist.ProcessMesh(shape=list(shape), dim_names=list(names))


def _values(t):
    return np.asarray(t.numpy())


def _num_shards(t, dim_size):
    sh = t._data.sharding
    return sh.num_devices if hasattr(sh, "num_devices") else dim_size


@pytest.fixture(scope="module")
def data():
    return np.arange(64, dtype=np.float32).reshape(8, 8)


def test_r_to_s(data):
    mesh = _mesh([4], "x")
    d = dist.shard_tensor(paddle.to_tensor(data), mesh,
                          [dist.Replicate()])
    s = dist.reshard(d, mesh, [dist.Shard(0)])
    np.testing.assert_array_equal(_values(s), data)
    assert s.placements is not None and \
        isinstance(s.placements[0], dist.Shard)
    assert s.placements[0].get_dim() == 0


def test_s_to_r(data):
    mesh = _mesh([4], "x")
    s = dist.shard_tensor(paddle.to_tensor(data), mesh, [dist.Shard(0)])
    r = dist.reshard(s, mesh, [dist.Replicate()])
    np.testing.assert_array_equal(_values(r), data)
    assert isinstance(r.placements[0], dist.Replicate)


def test_s_to_s_dim_swap(data):
    """Shard(0) -> Shard(1): the all-to-all transform (reference
    reshard_s_to_s.py)."""
    mesh = _mesh([4], "x")
    s0 = dist.shard_tensor(paddle.to_tensor(data), mesh, [dist.Shard(0)])
    s1 = dist.reshard(s0, mesh, [dist.Shard(1)])
    np.testing.assert_array_equal(_values(s1), data)
    assert s1.placements[0].get_dim() == 1


def test_nd_mesh_mixed_placements(data):
    """2-D mesh with Shard on one axis, Replicate on the other, then
    flip which axis shards (reference reshard_nd_mesh.py)."""
    mesh = _mesh([2, 2], ["dp", "mp"])
    t = dist.shard_tensor(paddle.to_tensor(data), mesh,
                          [dist.Shard(0), dist.Replicate()])
    np.testing.assert_array_equal(_values(t), data)
    flipped = dist.reshard(t, mesh,
                           [dist.Replicate(), dist.Shard(1)])
    np.testing.assert_array_equal(_values(flipped), data)
    pl = flipped.placements
    assert isinstance(pl[0], dist.Replicate) and \
        isinstance(pl[1], dist.Shard) and pl[1].get_dim() == 1


def test_cross_mesh(data):
    """Same transform across two DIFFERENT meshes (reference
    reshard_r_to_s_cross_mesh.py): device_put moves between mesh
    views."""
    mesh_a = _mesh([2], "x")
    mesh_b = _mesh([4], "y")
    t = dist.shard_tensor(paddle.to_tensor(data), mesh_a,
                          [dist.Shard(0)])
    moved = dist.reshard(t, mesh_b, [dist.Shard(1)])
    np.testing.assert_array_equal(_values(moved), data)
    assert moved.process_mesh is not None
    assert tuple(moved.process_mesh.shape) == (4,)


def test_partial_is_rejected_on_materialize(data):
    """Partial is an op-output state, not a materializable placement
    (our reshard lattice reduces it inside compiled ops)."""
    mesh = _mesh([4], "x")
    with pytest.raises(ValueError):
        dist.shard_tensor(paddle.to_tensor(data), mesh,
                          [dist.Partial()])


def test_grad_flows_through_reshard(data):
    """reshard is differentiable: grads flow back to the source
    (reference keeps reshard on the autograd tape)."""
    mesh = _mesh([4], "x")
    x = paddle.to_tensor(data)
    x.stop_gradient = False
    s = dist.shard_tensor(x, mesh, [dist.Shard(0)])
    r = dist.reshard(s, mesh, [dist.Replicate()])
    (r * 2).sum().backward()
    assert x.grad is not None
    np.testing.assert_allclose(_values(x.grad),
                               np.full_like(data, 2.0))


def test_reshard_preserves_dtype_under_amp(data):
    """shard/reshard are data movement, not compute: the AMP O2 hook
    must not downcast them (they run with amp=False)."""
    import paddle_tpu as paddle
    mesh = _mesh([4], "x")
    x = paddle.to_tensor(data)  # float32
    with paddle.amp.auto_cast(enable=True, level="O2"):
        s = dist.shard_tensor(paddle.to_tensor(data), mesh,
                              [dist.Shard(0)])
        x2 = paddle.to_tensor(data)
        x2.stop_gradient = False
        s2 = dist.shard_tensor(x2, mesh, [dist.Shard(0)])
        r = dist.reshard(s2, mesh, [dist.Replicate()])
    assert str(s.dtype).endswith("float32"), s.dtype
    assert str(s2.dtype).endswith("float32")
    assert str(r.dtype).endswith("float32")
