"""RNN family numerics vs torch (reference mechanism: rnn op tests in
test/legacy_test/test_rnn_op.py against numpy rnn reference; torch-CPU
is the oracle here). Weights are copied across so outputs must match
exactly up to float32 tolerance."""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
from paddle_tpu import nn

rs = np.random.RandomState(5)
I, H, T, B = 6, 8, 5, 3


def _copy_weights(ours, theirs, layer=0, reverse=False, bidir=False):
    """Copy one direction's weights from torch rnn to ours."""
    suffix = "_reverse" if reverse else ""
    w_ih = getattr(theirs, f"weight_ih_l{layer}{suffix}")
    w_hh = getattr(theirs, f"weight_hh_l{layer}{suffix}")
    b_ih = getattr(theirs, f"bias_ih_l{layer}{suffix}")
    b_hh = getattr(theirs, f"bias_hh_l{layer}{suffix}")
    ours.weight_ih._assign_array(
        paddle.to_tensor(w_ih.detach().numpy())._data)
    ours.weight_hh._assign_array(
        paddle.to_tensor(w_hh.detach().numpy())._data)
    ours.bias_ih._assign_array(
        paddle.to_tensor(b_ih.detach().numpy())._data)
    ours.bias_hh._assign_array(
        paddle.to_tensor(b_hh.detach().numpy())._data)


class TestCellsMatchTorch:
    def test_lstm_cell(self):
        ours = nn.LSTMCell(I, H)
        theirs = torch.nn.LSTM(I, H, num_layers=1, batch_first=True)
        _copy_weights(ours, theirs)
        x = rs.randn(B, T, I).astype(np.float32)
        h = np.zeros((B, H), np.float32)
        c = np.zeros((B, H), np.float32)
        hp, cp = paddle.to_tensor(h), paddle.to_tensor(c)
        outs = []
        for step in range(T):
            _, (hp, cp) = ours(paddle.to_tensor(x[:, step]), (hp, cp))
            outs.append(hp.numpy())
        ref, _ = theirs(torch.tensor(x))
        np.testing.assert_allclose(np.stack(outs, 1),
                                   ref.detach().numpy(), rtol=1e-4,
                                   atol=1e-5)

    def test_gru_cell(self):
        ours = nn.GRUCell(I, H)
        theirs = torch.nn.GRU(I, H, num_layers=1, batch_first=True)
        _copy_weights(ours, theirs)
        x = rs.randn(B, T, I).astype(np.float32)
        hp = paddle.to_tensor(np.zeros((B, H), np.float32))
        outs = []
        for step in range(T):
            _, hp = ours(paddle.to_tensor(x[:, step]), hp)
            outs.append(hp.numpy())
        ref, _ = theirs(torch.tensor(x))
        np.testing.assert_allclose(np.stack(outs, 1),
                                   ref.detach().numpy(), rtol=1e-4,
                                   atol=1e-5)

    def test_simple_rnn_cell(self):
        ours = nn.SimpleRNNCell(I, H)
        theirs = torch.nn.RNN(I, H, num_layers=1, batch_first=True)
        _copy_weights(ours, theirs)
        x = rs.randn(B, T, I).astype(np.float32)
        hp = paddle.to_tensor(np.zeros((B, H), np.float32))
        outs = []
        for step in range(T):
            _, hp = ours(paddle.to_tensor(x[:, step]), hp)
            outs.append(hp.numpy())
        ref, _ = theirs(torch.tensor(x))
        np.testing.assert_allclose(np.stack(outs, 1),
                                   ref.detach().numpy(), rtol=1e-4,
                                   atol=1e-5)


class TestLSTMLayer:
    def test_lstm_layer_forward_shapes_and_grad(self):
        lstm = nn.LSTM(I, H, num_layers=1)
        x = paddle.to_tensor(rs.randn(B, T, I).astype(np.float32),
                             stop_gradient=False)
        out, (h, c) = lstm(x)
        assert list(out.shape) == [B, T, H]
        assert list(h.shape)[-1] == H
        out.sum().backward()
        assert x.grad is not None
