"""paddle.distributed.rpc tests (reference model: test/rpc — sync/async
invoke + worker registry)."""
import numpy as np

from paddle_tpu.distributed import rpc
from paddle_tpu.distributed.ps.rpc import RpcClient


def _add(a, b):
    return a + b


def _mat(x):
    return (np.asarray(x) * 2).tolist()


def test_rpc_sync_async_and_registry():
    rpc.init_rpc("worker0", rank=0, world_size=1,
                 master_endpoint="127.0.0.1:29431")
    try:
        assert rpc.rpc_sync("worker0", _add, args=(2, 3)) == 5
        fut = rpc.rpc_async("worker0", _add, args=(4, 5))
        assert fut.wait() == 9
        assert rpc.get_worker_info("worker0").rank == 0
        # a remote peer registering + invoking over the socket path
        c = RpcClient("127.0.0.1:29431")
        infos = c.call("register", name="w1", rank=1, ip="127.0.0.1",
                       port=1)
        assert set(infos) == {"worker0", "w1"}
        import pickle
        out = c.call("invoke", fn=pickle.dumps(_mat),
                     args=pickle.dumps(([1, 2],)),
                     kwargs=pickle.dumps({}))
        assert out == [2, 4]
        c.close()
    finally:
        rpc.shutdown()
    assert rpc.get_all_worker_infos() == []
