"""Serving under fire (ISSUE 14): request lifecycle with deadlines and
cancellation, bounded-queue admission control with shedding policies,
/healthz readiness semantics, and fault-injected step-failure recovery
(retry envelope -> bisection quarantine of the poison request).

The fault-injection tests drive the env-gated `paddle_tpu._chaos` hook
points and carry the `chaos` marker (pytest.ini) so they are
selectable (`-m chaos`) / deselectable (`-m 'not chaos'`). The serving
harness is the 4-wide fake LM the metrics-server tests use — a few
tiny compiles total, the whole suite stays CPU-cheap.
"""
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.observability as obs
from paddle_tpu import _chaos, nn
from paddle_tpu.inference.admission import (AdmissionController,
                                            AdmissionRejected,
                                            RequestState,
                                            ServingStepError)
from paddle_tpu.inference.decode import (ContinuousBatchingSession,
                                         DecodeSession)
from paddle_tpu.observability import server as obs_server


class _TinyLM(nn.Layer):
    def __init__(self, vocab=17, hidden=4):
        super().__init__()
        self.emb = nn.Embedding(vocab, hidden)
        self.proj = nn.Linear(hidden, vocab)
        self._hidden = hidden

    def init_cache(self, batch_size, max_length=16):
        from paddle_tpu.inference.decode import init_static_cache
        return [init_static_cache(batch_size, max_length, 1,
                                  self._hidden)]

    def forward_with_cache(self, ids, caches):
        from paddle_tpu.inference.decode import cache_attention
        x = self.emb(ids)
        q = x.unsqueeze(2)
        out, c0 = cache_attention(q, q, q, caches[0])
        h = out.reshape([x.shape[0], x.shape[1], self._hidden])
        return self.proj(x + h), [c0]


@pytest.fixture(scope="module")
def lm():
    paddle.seed(13)
    return _TinyLM()


@pytest.fixture(autouse=True)
def _metrics_on():
    obs.enable()
    yield
    obs.enable()
    os.environ.pop(obs_server.PORT_ENV, None)
    while obs_server.shared_server() is not None:
        obs_server.session_finished()
    # a session leaked by a failing test must not degrade /healthz for
    # every later test
    obs_server._health_providers.clear()


def _prompt(rng, n=3):
    return rng.randint(0, 17, (n,)).astype(np.int32)


def _isolated(model, ids, n):
    """Greedy single-request oracle for output-parity assertions."""
    with DecodeSession(model, 16) as s:
        return s.generate(paddle.to_tensor(np.asarray(ids)[None]),
                          max_new_tokens=n).numpy()[0]


def _arm_chaos():
    os.environ[_chaos.ENV] = "on"
    _chaos.clear()


# ---------------------------------------------------------------- lifecycle
def test_request_state_machine_and_results(lm):
    rng = np.random.RandomState(0)
    sess = ContinuousBatchingSession(lm, max_slots=1, max_length=16)
    r1 = sess.submit(_prompt(rng), 4)
    r2 = sess.submit(_prompt(rng), 3)
    assert sess.status(r1) is RequestState.QUEUED
    sess.step()
    assert sess.status(r1) is RequestState.DECODING
    assert sess.status(r2) is RequestState.QUEUED
    res = sess.results()
    assert res[r1].ok and res[r1].state is RequestState.DONE
    assert res[r2].ok
    # delivered ids are released: unknown to status(), rid reusable
    assert sess.status(r1) is None
    assert sess._used_rids == set()
    sess.close()


def test_total_deadline_times_out_within_a_step(lm):
    rng = np.random.RandomState(1)
    obs.REGISTRY.reset()
    sess = ContinuousBatchingSession(lm, max_slots=2, max_length=64)
    slow = sess.submit(_prompt(rng), 60, deadline_s=0.05)
    ok = sess.submit(_prompt(rng), 3)
    t0 = time.perf_counter()
    res = sess.results()
    assert res[ok].ok
    assert res[slow].state is RequestState.TIMED_OUT
    # evicted with partial output, not hung: the drain finished well
    # before the 60-token budget could have
    assert len(res[slow].ids) < 3 + 60
    assert time.perf_counter() - t0 < 30
    assert obs.counter("serving.timed_out").value == 1
    # the slot was actually freed: a follow-up request runs to DONE
    r3 = sess.submit(_prompt(rng), 3)
    assert sess.results()[r3].ok
    sess.close()


def test_ttft_deadline_expires_queued_request(lm):
    """A request starved in the queue (slot held by a long decode)
    times out on its TTFT deadline without ever being admitted."""
    rng = np.random.RandomState(2)
    sess = ContinuousBatchingSession(lm, max_slots=1, max_length=64)
    hog = sess.submit(_prompt(rng), 40)
    starved = sess.submit(_prompt(rng), 3, ttft_deadline_s=0.0)
    res = sess.results()
    assert res[hog].ok
    assert res[starved].state is RequestState.TIMED_OUT
    assert len(res[starved].ids) == 3          # prompt only, no tokens
    sess.close()


def test_cancel_queued_and_running(lm):
    rng = np.random.RandomState(3)
    obs.REGISTRY.reset()
    p_keep = _prompt(rng, 4)
    sess = ContinuousBatchingSession(lm, max_slots=2, max_length=16)
    keep = sess.submit(p_keep, 5)
    victim_run = sess.submit(_prompt(rng), 8)
    victim_q = sess.submit(_prompt(rng), 8)    # waits: 2 slots busy
    sess.step()
    assert sess.cancel(victim_run) and sess.cancel(victim_q)
    assert not sess.cancel("nope")             # unknown id -> False
    res = sess.results()
    assert res[victim_run].state is RequestState.CANCELLED
    assert res[victim_q].state is RequestState.CANCELLED
    assert obs.counter("serving.cancelled").value == 2
    # the survivor is untouched: exact parity with an isolated decode
    np.testing.assert_array_equal(res[keep].ids,
                                  _isolated(lm, p_keep, 5))
    assert not sess.cancel(victim_run)         # already terminal
    sess.close()


# ---------------------------------------------------------- admission
def test_bounded_queue_rejects_newest(lm):
    rng = np.random.RandomState(4)
    obs.REGISTRY.reset()
    sess = ContinuousBatchingSession(lm, max_slots=1, max_length=16,
                                     max_queue=1)
    a = sess.submit(_prompt(rng), 3)           # next step's slot
    b = sess.submit(_prompt(rng), 3)           # the one queue seat
    with pytest.raises(AdmissionRejected, match="queue full"):
        sess.submit(_prompt(rng), 3)
    assert obs.counter("serving.rejected").value == 1
    res = sess.results()
    assert res[a].ok and res[b].ok
    sess.close()


def test_priority_lane_evicts_lower_priority(lm):
    rng = np.random.RandomState(5)
    sess = ContinuousBatchingSession(lm, max_slots=1, max_length=16,
                                     max_queue=1,
                                     shed_policy="priority")
    a = sess.submit(_prompt(rng), 3, priority=5)
    low = sess.submit(_prompt(rng), 3, priority=0)
    high = sess.submit(_prompt(rng), 3, priority=5)   # evicts `low`
    with pytest.raises(AdmissionRejected):
        sess.submit(_prompt(rng), 3, priority=5)      # no lower lane
    res = sess.results()
    assert res[low].state is RequestState.REJECTED
    assert res[a].ok and res[high].ok
    sess.close()


def test_admission_controller_validates_config():
    with pytest.raises(ValueError, match="policy"):
        AdmissionController(policy="drop_everything")
    with pytest.raises(ValueError, match="max_queue"):
        AdmissionController(max_queue=0)


def test_overload_sheds_fast_and_latency_stays_bounded(lm):
    """Acceptance rung: 2x slot capacity sustained. The bounded queue
    sheds with fast rejections; accepted requests' latency reaches a
    steady state instead of growing with offered load (shed, never
    collapse), and ZERO requests hang."""
    rng = np.random.RandomState(6)
    before = obs.take_snapshot()
    sess = ContinuousBatchingSession(lm, max_slots=2, max_length=16,
                                     max_queue=2)
    submit_t, finish_t = {}, {}
    accepted, rejected = [], 0
    rounds = 12
    for _ in range(rounds):
        # offered load: 2x the slot count, every round — strictly more
        # than the two steps below can serve
        for _ in range(2 * 2):
            try:
                t0 = time.perf_counter()
                rid = sess.submit(_prompt(rng), 3)
                submit_t[rid] = t0
                accepted.append(rid)
            except AdmissionRejected:
                rejected += 1
        for _ in range(2):
            for rid in sess.step():
                finish_t[rid] = time.perf_counter()
        # the backlog is BOUNDED by construction — this is what keeps
        # accepted-request latency flat under sustained overload
        assert len(sess._queue) <= 2 + 2
    res = sess.results()
    for rid in res:
        finish_t.setdefault(rid, time.perf_counter())
    d = obs.delta(before, obs.take_snapshot())
    assert rejected > 0
    assert d.value("serving.rejected") == rejected
    # zero hung: every accepted request reached DONE and was delivered
    assert sorted(res) == sorted(accepted)
    assert all(r.ok for r in res.values())
    assert sess._used_rids == set()
    # the telemetry window saw every accepted completion
    hist = d.hist("serving.request_latency_s")
    assert hist["count"] == len(accepted)
    # shed-not-collapse: late arrivals wait no longer than early ones
    # (+compile warmup makes the early quarter the SLOW one; the bound
    # is generous because CI wall clocks are noisy)
    lats = [finish_t[r] - submit_t[r] for r in accepted]
    q = max(1, len(lats) // 4)
    early, late = lats[:q], lats[-q:]
    assert (sum(late) / len(late)
            <= 6 * sum(early) / len(early) + 0.25), (early, late)
    p99 = obs.REGISTRY.histogram("serving.request_latency_s")\
        .percentile(0.99)
    assert p99 is not None and p99 <= max(lats) + 1e-6
    sess.close()


# --------------------------------------------------- readiness (/healthz)
def test_healthz_degrades_under_pressure_and_recovers(lm):
    import json
    import urllib.error
    import urllib.request

    os.environ[obs_server.PORT_ENV] = "0"
    rng = np.random.RandomState(7)
    sess = ContinuousBatchingSession(lm, max_slots=1, max_length=16,
                                     max_queue=2)
    srv = obs_server.shared_server()
    assert srv is not None
    with urllib.request.urlopen(srv.url + "/healthz", timeout=5) as r:
        assert r.status == 200 and json.loads(r.read()) == {
            "status": "ok"}
    for _ in range(3):                        # fill slot + queue
        sess.submit(_prompt(rng), 3)
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(srv.url + "/healthz", timeout=5)
    assert ei.value.code == 503
    payload = json.loads(ei.value.read())
    assert payload["status"] == "degraded" and payload["reasons"]
    sess.step()
    assert obs.gauge("serving.degraded").value == 1.0
    sess.results()                            # drain the backlog
    with urllib.request.urlopen(srv.url + "/healthz", timeout=5) as r:
        assert r.status == 200                # ready again
    sess.close()
    # after close the session's provider is unregistered: a fresh
    # server (new session) must not inherit stale pressure
    assert obs_server.health_status()[0] is True


# ------------------------------------------------------ fault injection
@pytest.mark.chaos
def test_transient_step_failure_retried_to_success(lm):
    _arm_chaos()
    obs.REGISTRY.reset()
    rng = np.random.RandomState(8)
    p = _prompt(rng)
    _chaos.install("serving.decode_step", kind="error", times=2)
    sess = ContinuousBatchingSession(lm, max_slots=2, max_length=16)
    rid = sess.submit(p, 4)
    res = sess.results()
    assert res[rid].ok
    np.testing.assert_array_equal(res[rid].ids, _isolated(lm, p, 4))
    assert obs.counter("serving.step_retries").value >= 2
    assert obs.counter("serving.quarantined").value == 0
    sess.close()


@pytest.mark.chaos
def test_persistent_poison_request_is_bisected_out(lm):
    """Acceptance: an injected persistent step failure (active only
    while the poison request's slot participates) fails ONLY that
    request; the session and every other in-flight request run to
    completion with outputs identical to isolated decodes."""
    _arm_chaos()
    obs.REGISTRY.reset()
    rng = np.random.RandomState(9)
    prompts = [_prompt(rng, n) for n in (3, 4, 3)]
    sess = ContinuousBatchingSession(lm, max_slots=3, max_length=16)
    rids = [sess.submit(p, 5) for p in prompts]
    sess.step()                                # all three admitted
    poison_rid = rids[1]
    poison_slot = next(s for s, req in sess._running.items()
                       if req.rid == poison_rid)
    _chaos.install(
        "serving.decode_step", kind="error",
        match=lambda ctx: poison_slot in ctx.get("slots", ()))
    res = sess.results()
    assert res[poison_rid].state is RequestState.FAILED
    assert "chaos" in res[poison_rid].error
    assert obs.counter("serving.quarantined").value == 1
    for rid, p in zip(rids, prompts):
        if rid == poison_rid:
            continue
        assert res[rid].ok
        np.testing.assert_array_equal(res[rid].ids,
                                      _isolated(lm, p, 5))
    # the session stays alive: the freed slot serves a NEW request
    _chaos.clear()
    r_new = sess.submit(prompts[0], 4)
    assert sess.results()[r_new].ok
    sess.close()


@pytest.mark.chaos
def test_admit_failure_quarantines_only_that_request(lm):
    _arm_chaos()
    obs.REGISTRY.reset()
    rng = np.random.RandomState(10)
    p_ok = _prompt(rng)
    sess = ContinuousBatchingSession(lm, max_slots=2, max_length=16,
                                     step_backoff_s=0.0)
    bad = sess.submit(_prompt(rng), 4)
    good = sess.submit(p_ok, 4)
    _chaos.install("serving.admit_step", kind="alloc",
                   match=lambda ctx: ctx.get("rid") == bad)
    res = sess.results()
    assert res[bad].state is RequestState.FAILED
    assert "RESOURCE_EXHAUSTED" in res[bad].error
    assert res[good].ok
    np.testing.assert_array_equal(res[good].ids,
                                  _isolated(lm, p_ok, 4))
    assert obs.counter("serving.quarantined").value == 1
    sess.close()


@pytest.mark.chaos
def test_slow_step_chaos_trips_the_deadline(lm):
    """An injected slow step (transport stall) makes the in-flight
    request blow its total deadline: it returns TIMED_OUT instead of
    stretching the tail."""
    _arm_chaos()
    rng = np.random.RandomState(11)
    _chaos.install("serving.decode_step", kind="slow", seconds=0.06)
    sess = ContinuousBatchingSession(lm, max_slots=1, max_length=64)
    rid = sess.submit(_prompt(rng), 50, deadline_s=0.15)
    res = sess.results()
    assert res[rid].state is RequestState.TIMED_OUT
    sess.close()


@pytest.mark.chaos
def test_step_wide_failure_raises_and_session_stays_closeable(lm):
    """When DISJOINT slot subsets keep failing, bisection refuses to
    quarantine innocents: step()/run() raise ServingStepError, and the
    exception path still releases the metrics-server refcount via the
    session lifecycle (context exit / close)."""
    os.environ[obs_server.PORT_ENV] = "0"
    _arm_chaos()
    rng = np.random.RandomState(12)
    with ContinuousBatchingSession(lm, max_slots=2, max_length=16,
                                   step_backoff_s=0.0) as sess:
        assert obs_server.shared_server() is not None
        sess.submit(_prompt(rng), 4)
        sess.submit(_prompt(rng), 4)
        sess.step()
        _chaos.install("serving.decode_step", kind="error")
        with pytest.raises(ServingStepError, match="disjoint"):
            sess.run()
    # exception path through run(): the context exit released the ref
    assert obs_server.shared_server() is None
    sess.close()                               # double-close idempotent


@pytest.mark.chaos
def test_chaos_env_spec_and_alloc_site():
    """The env-spec form (`site:kind:arg`) works without any
    programmatic install — here an allocation failure at the cache
    allocation site, budget 1."""
    from paddle_tpu._chaos import ChaosAllocError
    from paddle_tpu.inference.decode import init_static_cache
    _chaos.clear()
    os.environ[_chaos.ENV] = "serving.cache_alloc:alloc:1"
    with pytest.raises(ChaosAllocError, match="RESOURCE_EXHAUSTED"):
        init_static_cache(1, 8, 1, 4)
    init_static_cache(1, 8, 1, 4)              # budget spent: fine now


def test_chaos_rules_inert_without_env(lm):
    """Programmatic rules NEVER fire unless PADDLE_TPU_CHAOS is set —
    a stray import/install cannot inject faults into production."""
    os.environ.pop(_chaos.ENV, None)
    _chaos.clear()
    _chaos.install("serving.decode_step", kind="error")
    try:
        rng = np.random.RandomState(14)
        sess = ContinuousBatchingSession(lm, max_slots=1, max_length=16)
        rid = sess.submit(_prompt(rng), 3)
        assert sess.results()[rid].ok
        sess.close()
    finally:
        _chaos.clear()


def test_cancel_mid_sync_window_does_not_deadlock_results(lm):
    """Regression (review finding): with sync_every>1, cancelling the
    only running request mid-window used to wedge results() — pending
    below the sync quantum blocked draining, the empty running set
    blocked dispatch, and the non-empty pending blocked admission.
    The partial window must flush so queued work proceeds."""
    rng = np.random.RandomState(16)
    sess = ContinuousBatchingSession(lm, max_slots=1, max_length=16,
                                     sync_every=3)
    victim = sess.submit(_prompt(rng), 8)
    queued = sess.submit(_prompt(rng), 3)
    sess.step()                                # 1 < sync_every pending
    assert sess.cancel(victim)
    t0 = time.perf_counter()
    res = sess.results()
    assert time.perf_counter() - t0 < 30       # terminates
    assert res[victim].state is RequestState.CANCELLED
    assert res[queued].ok
    sess.close()


def test_abandoned_session_is_not_pinned_by_health_registry(lm):
    """Regression (review finding): the health-provider registration
    must hold the session only weakly — a session dropped without
    close() still gets finalized (its provider then reports None)."""
    import gc
    import weakref

    rng = np.random.RandomState(17)
    sess = ContinuousBatchingSession(lm, max_slots=1, max_length=16,
                                     max_queue=1)
    sess.submit(_prompt(rng), 3)
    sess.submit(_prompt(rng), 3)               # backlog: degraded
    assert obs_server.health_status()[0] is False
    ref = weakref.ref(sess)
    del sess
    gc.collect()
    assert ref() is None, "session leaked via the provider registry"
    # the dead provider reports healthy, not stale pressure
    assert obs_server.health_status()[0] is True


# ------------------------------------------------------------ close()
def test_close_cancels_inflight_and_is_idempotent(lm):
    obs.REGISTRY.reset()
    rng = np.random.RandomState(15)
    sess = ContinuousBatchingSession(lm, max_slots=1, max_length=16)
    sess.submit(_prompt(rng), 8)
    sess.submit(_prompt(rng), 8)               # queued
    sess.step()
    t0 = time.perf_counter()
    sess.close()
    assert time.perf_counter() - t0 < 5        # no hang on futures
    assert sess._used_rids == set()
    assert not sess._running and not sess._queue and not sess._pending
    assert obs.counter("serving.cancelled").value == 2
    sess.close()                               # idempotent
    assert sess._used_rids == set()
