"""Monolithic Pallas attention kernel numerics (interpret mode on CPU;
the on-device win is recorded in benchmarks/probes/_simple_attn_bench.py:
1.33 vs 2.31 ms/layer fwd+bwd against the library flash kernel)."""
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.simple_attention import (attention_bhsd,
                                                    supported)

B, H, S, D = 2, 3, 256, 128


def naive(q, k, v, causal=True):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(D)
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, -1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.fixture(scope="module")
def qkv():
    key = jax.random.PRNGKey(0)
    mk = lambda i: jax.random.normal(jax.random.fold_in(key, i),
                                     (B, H, S, D), jnp.float32)
    return mk(0), mk(1), mk(2)


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_naive(qkv, causal):
    q, k, v = qkv
    out = attention_bhsd(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(naive(q, k, v, causal)),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("argi", [0, 1, 2])
def test_grads_match_naive(qkv, argi):
    q, k, v = qkv
    args = [q, k, v]

    def fp(t):
        a = list(args)
        a[argi] = t
        return attention_bhsd(*a, causal=True, interpret=True).sum()

    def fn(t):
        a = list(args)
        a[argi] = t
        return naive(*a, True).sum()

    np.testing.assert_allclose(np.asarray(jax.grad(fp)(args[argi])),
                               np.asarray(jax.grad(fn)(args[argi])),
                               rtol=2e-3, atol=2e-4)


def test_supported_gate():
    assert supported((8, 8, 1024, 128), jnp.bfloat16)
    assert not supported((8, 8, 4096, 128), jnp.bfloat16)  # VMEM blow
    assert not supported((8, 8, 1000, 128), jnp.bfloat16)  # not tiled


class TestQBlockKernel:
    """Q-blocked variant for longer sequences (simple_attention2):
    streams q in blocks, accumulates dk/dv across the q-block grid."""

    def test_fwd_and_dk_accumulation(self):
        from paddle_tpu.ops.pallas.simple_attention2 import (
            attention_bhsd as qb, _pick_bq)
        S2 = 1024
        assert _pick_bq(S2, 128, 4) < S2  # blocking actually engaged? 
        key = jax.random.PRNGKey(1)
        mk = lambda i: jax.random.normal(jax.random.fold_in(key, i),
                                         (1, 2, S2, 128), jnp.float32)
        q, k, v = mk(0), mk(1), mk(2)

        def dense(q, k, v):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(128)
            mask = jnp.tril(jnp.ones((S2, S2), bool))
            s = jnp.where(mask, s, -1e30)
            return jnp.einsum("bhqk,bhkd->bhqd",
                              jax.nn.softmax(s, -1), v)

        out = qb(q, k, v, causal=True, interpret=True)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(dense(q, k, v)),
                                   rtol=2e-4, atol=2e-5)
        gk = jax.grad(lambda t: qb(q, t, v, causal=True,
                                   interpret=True).sum())(k)
        gk_ref = jax.grad(lambda t: dense(q, t, v).sum())(k)
        np.testing.assert_allclose(np.asarray(gk), np.asarray(gk_ref),
                                   rtol=2e-3, atol=2e-4)

    def test_supported_ranges(self):
        from paddle_tpu.ops.pallas import simple_attention2 as sa2
        assert sa2.supported((4, 8, 2048, 128), jnp.bfloat16)
        # S=4096 needs whole-k/v f32 in VMEM (~8 MB) + strips: over
        # budget -> falls back to the library streaming flash kernel
        assert not sa2.supported((1, 8, 4096, 128), jnp.bfloat16)
        assert not sa2.supported((1, 8, 2048, 100), jnp.bfloat16)
