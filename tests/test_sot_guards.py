"""SOT-equivalent guarded multi-specialization JIT (jit/__init__.py).

Reference: paddle.jit.sot builds guarded partial graphs via bytecode
simulation (sot/opcode_translator/executor/opcode_executor.py:1603);
on a guard failure it re-specializes instead of staying eager.

TPU-native redesign under test: python control flow on tensor values
surfaces as Tensor scalarization; a probe/replay interceptor turns
each scalarization outcome into a guard, every guard set becomes one
compiled specialization, and the compiled program re-emits the guard
predicates so each call validates its specialization and de-optimizes
through an eager probe on mismatch.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import (MAX_SPECIALIZATIONS, StaticFunction,
                            sot_report, to_static)


def _arr(v):
    return paddle.to_tensor(np.asarray(v, np.float32))


def test_value_dependent_branch_two_specializations():
    calls = []

    @to_static
    def f(x):
        calls.append(1)
        if (x.mean() > 0):          # python branch on a tensor value
            return x * 2.0
        return x - 1.0

    pos = _arr([1.0, 2.0])
    neg = _arr([-1.0, -2.0])
    # 1st positive call: skeleton breaks -> eager probe + spec A
    np.testing.assert_allclose(np.asarray(f(pos)._data), [2.0, 4.0])
    # 2nd positive call: compiled spec A (guards pass)
    np.testing.assert_allclose(np.asarray(f(pos)._data), [2.0, 4.0])
    # negative: guard mismatch -> probe + spec B
    np.testing.assert_allclose(np.asarray(f(neg)._data), [-2.0, -3.0])
    # both paths now compiled; alternate freely
    np.testing.assert_allclose(np.asarray(f(neg)._data), [-2.0, -3.0])
    np.testing.assert_allclose(np.asarray(f(pos)._data), [2.0, 4.0])

    specs = list(f.specializations().values())[0]
    assert len(specs) == 2, specs
    assert ("bool", True) in [d for ds in specs for d in ds]
    rep = f.report()["signatures"][0]
    assert rep["fallback"] is None
    assert rep["graph_breaks"] >= 2
    # compiled hits: calls 2, 4, 5 ran the executable, probes only 1, 3
    assert sum(s["hits"] for s in rep["specializations"]) == 3
    assert len(calls) > 0


def test_no_branching_single_spec_no_probe():
    @to_static
    def f(x):
        return x * 3.0

    f(_arr([1.0]))
    f(_arr([2.0]))
    rep = f.report()["signatures"][0]
    assert len(rep["specializations"]) == 1
    assert rep["specializations"][0]["decisions"] == ()
    assert rep["eager_probes"] == 0
    assert rep["graph_breaks"] == 0


def test_int_specialization_guard():
    @to_static
    def f(x):
        k = int(x.sum()) % 2        # python int of a tensor value
        if k == 0:
            return x + 10.0
        return x - 10.0

    even = _arr([2.0, 2.0])
    odd = _arr([2.0, 3.0])
    np.testing.assert_allclose(np.asarray(f(even)._data), [12.0, 12.0])
    np.testing.assert_allclose(np.asarray(f(odd)._data), [-8.0, -7.0])
    np.testing.assert_allclose(np.asarray(f(even)._data), [12.0, 12.0])
    specs = list(f.specializations().values())[0]
    assert len(specs) == 2
    kinds = {d[0] for ds in specs for d in ds}
    assert "int" in kinds


def test_item_and_float_guards():
    @to_static
    def f(x):
        if x.max().item() > 5.0:
            return x / 2.0
        return x

    np.testing.assert_allclose(np.asarray(f(_arr([8.0]))._data), [4.0])
    np.testing.assert_allclose(np.asarray(f(_arr([1.0]))._data), [1.0])
    np.testing.assert_allclose(np.asarray(f(_arr([8.0]))._data), [4.0])
    assert len(list(f.specializations().values())[0]) == 2


def test_volatile_float_guard_falls_back_fast():
    """float(loss)-style guards never repeat; after the second distinct
    value the signature goes eager instead of burning one XLA compile
    per call."""
    @to_static
    def f(x):
        return x + float(x.sum())   # a new float guard every call

    f(_arr([1.0]))                  # probe + spec for value 1.0
    f(_arr([2.0]))                  # second distinct float: one more
    with pytest.warns(UserWarning, match="volatile float"):
        f(_arr([3.0]))              # third distinct value: go eager
    rep = f.report()["signatures"][0]
    assert rep["fallback"] == "volatile float guard"
    assert len(rep["specializations"]) <= 3
    np.testing.assert_allclose(np.asarray(f(_arr([50.0]))._data), [100.0])


def test_specialization_limit_falls_back():
    @to_static
    def f(x):
        k = int(x.sum())            # a new int guard every call
        return x + float(k)

    for i in range(MAX_SPECIALIZATIONS + 2):
        v = float(i)
        with pytest.warns(UserWarning) if i == MAX_SPECIALIZATIONS \
                else _nullcontext():
            out = f(_arr([v]))
        np.testing.assert_allclose(np.asarray(out._data), [2 * v])
    rep = f.report()["signatures"][0]
    assert rep["fallback"] == "specialization limit exceeded"
    # still correct after fallback
    np.testing.assert_allclose(np.asarray(f(_arr([50.0]))._data), [100.0])


def test_branches_with_different_pytree_structures():
    """Each specialization owns its out_spec: branches may return
    different structures."""
    @to_static
    def f(x):
        if (x.mean() > 0):
            return x * 2.0
        return (x - 1.0, x.sum())

    pos, neg = _arr([1.0]), _arr([-1.0])
    np.testing.assert_allclose(np.asarray(f(pos)._data), [2.0])
    np.testing.assert_allclose(np.asarray(f(pos)._data), [2.0])
    out = f(neg)
    assert isinstance(out, tuple) and len(out) == 2
    out = f(neg)                    # compiled tuple-branch
    assert isinstance(out, tuple) and len(out) == 2
    # alternate back: compiled single-tensor branch, right structure
    np.testing.assert_allclose(np.asarray(f(pos)._data), [2.0])
    out = f(neg)
    assert isinstance(out, tuple)
    np.testing.assert_allclose(np.asarray(out[0]._data), [-2.0])


def test_nested_static_function_inlines():
    """A to_static function called inside another to_static trace
    inlines into the outer program instead of going eager-fallback."""
    @to_static
    def inner(x):
        if (x.mean() > 0):
            return x * 3.0
        return x

    @to_static
    def outer(x):
        return inner(x) + 1.0

    np.testing.assert_allclose(np.asarray(outer(_arr([2.0]))._data),
                               [7.0])
    np.testing.assert_allclose(np.asarray(outer(_arr([2.0]))._data),
                               [7.0])
    # inner keeps working standalone, still compiled
    np.testing.assert_allclose(np.asarray(inner(_arr([2.0]))._data),
                               [6.0])
    assert inner.report()["signatures"][0]["fallback"] is None
    assert outer.report()["signatures"][0]["fallback"] is None


class _nullcontext:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def test_untraceable_numpy_falls_back_per_signature():
    @to_static
    def f(x):
        return _arr(np.asarray(x.numpy()) * 2.0)

    with pytest.warns(UserWarning, match="not traceable"):
        out = f(_arr([3.0]))
    np.testing.assert_allclose(np.asarray(out._data), [6.0])
    out = f(_arr([4.0]))
    np.testing.assert_allclose(np.asarray(out._data), [8.0])
    assert f.report()["signatures"][0]["fallback"] is not None


def test_train_step_with_loss_conditional_stays_compiled():
    """A train step whose python logic branches on the loss value (a
    hand-rolled skip-on-spike heuristic) keeps two compiled
    specializations and still trains."""
    import paddle_tpu.nn as nn

    model = nn.Linear(4, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())

    def step(xb, yb):
        pred = model(xb)
        loss = ((pred - yb) ** 2).mean()
        if (loss < 100.0):          # value-dependent python branch
            loss.backward()
            opt.step()
            opt.clear_grad()
        return loss

    sstep = to_static(step, objs=[model, opt])
    rng = np.random.RandomState(0)
    xb = _arr(rng.randn(8, 4))
    yb = _arr(rng.randn(8, 1))
    losses = [float(sstep(xb, yb)._data) for _ in range(5)]
    assert losses[-1] < losses[0]
    big = _arr(rng.randn(8, 1) * 1000.0)
    sstep(xb, big)                  # takes the skip branch
    specs = list(sstep.specializations().values())
    flat = [d for sig in specs for d in sig]
    assert len(flat) >= 2
    assert sstep.report()["signatures"][0]["fallback"] is None


def test_sot_report_module_level():
    @to_static
    def f(x):
        return x + 1.0

    f(_arr([1.0]))
    reps = sot_report()
    assert any(r["function"].endswith("f") for r in reps)
