"""paddle.sparse parity tests (reference: python/paddle/sparse over phi
sparse_coo kernels; test model: test/legacy_test/test_sparse_*_op.py)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.sparse as sp


def _coo():
    return sp.sparse_coo_tensor([[0, 1, 2], [1, 2, 0]], [1.0, 2.0, 3.0],
                                [3, 3])


def test_unary_value_ops():
    x = _coo()
    np.testing.assert_allclose(sp.sqrt(x).values().numpy(),
                               np.sqrt([1.0, 2.0, 3.0]), rtol=1e-6)
    np.testing.assert_allclose(sp.square(x).values().numpy(), [1, 4, 9])
    np.testing.assert_allclose(sp.neg(x).values().numpy(), [-1, -2, -3])
    np.testing.assert_allclose(sp.pow(x, 2).values().numpy(), [1, 4, 9])
    assert sp.cast(x, value_dtype="float16").values().numpy().dtype == \
        np.float16
    assert not sp.isnan(x).values().numpy().any()
    # zero-preservation: dense of sin(x) matches sin of dense
    np.testing.assert_allclose(sp.sin(x).to_dense().numpy(),
                               np.sin(x.to_dense().numpy()), rtol=1e-6)


def test_binary_and_matrix_ops():
    x = _coo()
    y = sp.sparse_coo_tensor([[0, 1, 2], [1, 2, 0]], [10.0, 20.0, 30.0],
                             [3, 3])
    np.testing.assert_allclose(sp.subtract(y, x).values().numpy(),
                               [9, 18, 27])
    np.testing.assert_allclose(sp.multiply(x, y).values().numpy(),
                               [10, 40, 90])
    np.testing.assert_allclose(sp.divide(y, x).values().numpy(),
                               [10, 10, 10])
    v = paddle.to_tensor([1.0, 1.0, 1.0])
    np.testing.assert_allclose(sp.mv(x, v).numpy(), [1, 2, 3])
    eye = paddle.to_tensor(np.eye(3, dtype=np.float32))
    out = sp.addmm(eye, x, eye, beta=2.0, alpha=1.0).numpy()
    np.testing.assert_allclose(out, 2 * np.eye(3) + x.to_dense().numpy())
    mm = sp.masked_matmul(eye, eye, x)
    np.testing.assert_allclose(mm.to_dense().numpy(),
                               np.eye(3) * (x.to_dense().numpy() != 0))


def test_structure_ops():
    x = _coo()
    np.testing.assert_allclose(sp.transpose(x, [1, 0]).to_dense().numpy(),
                               x.to_dense().numpy().T)
    np.testing.assert_allclose(sp.sum(x, 0).to_dense().numpy(),
                               x.to_dense().numpy().sum(0))
    assert float(sp.sum(x).numpy()) == 6.0
    assert sp.coalesce(x).nnz() == 3
    assert sp.is_same_shape(x, _coo())
    np.testing.assert_allclose(
        sp.reshape(x, [9, 1]).to_dense().numpy().ravel(),
        x.to_dense().numpy().ravel())
    sl = sp.slice(x, [0], [0], [2])
    np.testing.assert_allclose(sl.to_dense().numpy(),
                               x.to_dense().numpy()[:2])


def test_pca_lowrank_reconstructs():
    rng = np.random.RandomState(0)
    base = rng.randn(8, 2) @ rng.randn(2, 6)
    x = paddle.to_tensor(base.astype(np.float32))
    u, s, v = sp.pca_lowrank(x, q=2, center=False)
    rec = u.numpy() @ np.diag(s.numpy()) @ v.numpy().T
    np.testing.assert_allclose(rec, base, atol=1e-3)


def test_nn_layers():
    neg = sp.sparse_coo_tensor([[0, 1], [1, 0]], [-1.0, 2.0], [2, 2])
    np.testing.assert_allclose(sp.nn.ReLU()(neg).values().numpy(), [0, 2])
    x = _coo()
    rows = sp.nn.Softmax()(x).to_dense().numpy().sum(1)
    np.testing.assert_allclose(rows, [1, 1, 1], rtol=1e-6)
    xs = sp.to_sparse_coo(paddle.to_tensor(
        np.random.rand(2, 2, 2, 2, 4).astype(np.float32)))
    bn = sp.nn.BatchNorm(4)
    assert bn(xs).to_dense().shape == [2, 2, 2, 2, 4]
    out = sp.nn.SubmConv3D(4, 8, 3)(xs)
    assert out.to_dense().shape == [2, 2, 2, 2, 8]
    assert sp.nn.MaxPool3D(2)(xs).to_dense().shape == [2, 1, 1, 1, 4]


def test_submanifold_preserves_support():
    dense = np.zeros((1, 4, 4, 4, 2), np.float32)
    dense[0, 1, 1, 1] = 1.0
    dense[0, 2, 3, 0] = 2.0
    xs = sp.to_sparse_coo(paddle.to_tensor(dense), sparse_dim=4)
    out = sp.nn.SubmConv3D(2, 3, 3)(xs)
    od = out.to_dense().numpy()
    mask = (np.abs(od).sum(-1) != 0)
    in_mask = (np.abs(dense).sum(-1) != 0)
    assert (mask == in_mask).all(), "submanifold support changed"
