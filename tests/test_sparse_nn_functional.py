"""paddle.sparse.nn.functional oracle tests (reference:
python/paddle/sparse/nn/functional/{conv,pooling,activation,
transformer}.py).

Oracles: torch dense conv/pool on the densified input (independent of
the jax implementation path), numpy masked-softmax for attention, and
the submanifold support-preservation invariant.
"""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
import paddle_tpu.sparse as sp

F = sp.nn.functional


def _rand_sparse_ndhwc(shape, density=0.3, seed=0):
    rng = np.random.RandomState(seed)
    dense = rng.randn(*shape).astype(np.float32)
    mask = rng.rand(*shape[:-1]) < density      # site-level sparsity
    dense = dense * mask[..., None]
    return sp.to_sparse_coo(paddle.to_tensor(dense),
                            sparse_dim=len(shape) - 1), dense


def test_conv3d_matches_torch_dense():
    xs, dense = _rand_sparse_ndhwc((2, 6, 6, 6, 3))
    rng = np.random.RandomState(1)
    w = rng.randn(3, 3, 3, 3, 5).astype(np.float32) * 0.1
    b = rng.randn(5).astype(np.float32)
    out = F.conv3d(xs, paddle.to_tensor(w), paddle.to_tensor(b),
                   stride=2, padding=1).to_dense().numpy()
    ref = torch.nn.functional.conv3d(
        torch.tensor(dense).permute(0, 4, 1, 2, 3),
        torch.tensor(w).permute(4, 3, 0, 1, 2), torch.tensor(b),
        stride=2, padding=1).permute(0, 2, 3, 4, 1).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_conv2d_matches_torch_dense():
    xs, dense = _rand_sparse_ndhwc((2, 8, 8, 3))
    rng = np.random.RandomState(2)
    w = rng.randn(3, 3, 3, 4).astype(np.float32) * 0.1
    out = F.conv2d(xs, paddle.to_tensor(w), stride=1,
                   padding=1).to_dense().numpy()
    ref = torch.nn.functional.conv2d(
        torch.tensor(dense).permute(0, 3, 1, 2),
        torch.tensor(w).permute(3, 2, 0, 1),
        stride=1, padding=1).permute(0, 2, 3, 1).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("fn", [F.subm_conv3d, F.subm_conv3d_igemm])
def test_subm_conv3d_support_and_values(fn):
    xs, dense = _rand_sparse_ndhwc((1, 5, 5, 5, 2), density=0.2)
    rng = np.random.RandomState(3)
    w = rng.randn(3, 3, 3, 2, 4).astype(np.float32) * 0.1
    out = fn(xs, paddle.to_tensor(w))
    # 1) submanifold rule: output support == input support
    in_sites = {tuple(r) for r in np.asarray(xs.indices().numpy()).T}
    out_sites = {tuple(r[:4]) for r in
                 np.asarray(out._bcoo.indices)}
    assert out_sites == in_sites
    # 2) values at active sites match the torch dense conv there
    ref = torch.nn.functional.conv3d(
        torch.tensor(dense).permute(0, 4, 1, 2, 3),
        torch.tensor(w).permute(4, 3, 0, 1, 2),
        padding=1).permute(0, 2, 3, 4, 1).numpy()
    out_d = out.to_dense().numpy()
    for site in in_sites:
        np.testing.assert_allclose(out_d[site], ref[site],
                                   rtol=1e-4, atol=1e-5)
    # 3) inactive sites stay exactly zero
    inactive = np.ones(out_d.shape[:4], bool)
    for site in in_sites:
        inactive[site] = False
    assert np.all(out_d[inactive] == 0)


def test_subm_conv2d_support_preserved():
    xs, _ = _rand_sparse_ndhwc((2, 6, 6, 3), density=0.25, seed=5)
    rng = np.random.RandomState(4)
    w = rng.randn(3, 3, 3, 6).astype(np.float32)
    for fn in (F.subm_conv2d, F.subm_conv2d_igemm):
        out = fn(xs, paddle.to_tensor(w))
        in_sites = {tuple(r) for r in np.asarray(xs.indices().numpy()).T}
        out_sites = {tuple(r[:3]) for r in np.asarray(out._bcoo.indices)}
        assert out_sites == in_sites


def test_max_pool3d_matches_torch():
    xs, dense = _rand_sparse_ndhwc((2, 4, 4, 4, 3), density=0.5, seed=6)
    out = F.max_pool3d(xs, 2).to_dense().numpy()
    ref = torch.nn.functional.max_pool3d(
        torch.tensor(dense).permute(0, 4, 1, 2, 3), 2)
    ref = ref.permute(0, 2, 3, 4, 1).numpy()
    # empty windows densify to 0 on the sparse path; torch sees the
    # zeros too (dense holds 0 at inactive sites) — only positive
    # entries can differ... they cannot: max(0, negatives)=0 both ways
    np.testing.assert_allclose(out, np.maximum(ref, 0), atol=1e-6)


def test_activations_value_semantics():
    x = sp.sparse_coo_tensor([[0, 0, 1], [0, 2, 1]],
                             [-3.0, 7.5, 2.0], (2, 3))
    np.testing.assert_allclose(F.relu(x).values().numpy(), [0, 7.5, 2])
    np.testing.assert_allclose(F.relu6(x).values().numpy(), [0, 6, 2])
    np.testing.assert_allclose(F.leaky_relu(x, 0.1).values().numpy(),
                               [-0.3, 7.5, 2])


def test_functional_softmax_stored_entries_only():
    x = sp.sparse_coo_tensor([[0, 0, 1], [0, 2, 1]],
                             [1.0, 3.0, 2.0], (2, 3))
    out = F.softmax(x).to_dense().numpy()
    e = np.exp([1.0, 3.0])
    np.testing.assert_allclose(out[0, [0, 2]], e / e.sum(), rtol=1e-6)
    np.testing.assert_allclose(out[1, 1], 1.0)
    assert out[0, 1] == 0          # missing entry stays structurally 0


def _np_masked_attention(q, k, v, keep):
    d = q.shape[-1]
    logits = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    logits = np.where(keep, logits, -np.inf)
    m = logits.max(-1, keepdims=True)
    p = np.exp(logits - m)
    p = np.where(keep, p, 0.0)
    p = p / np.maximum(p.sum(-1, keepdims=True), 1e-30)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


def test_sparse_attention_vs_numpy_oracle():
    rng = np.random.RandomState(7)
    b, h, s, d = 2, 2, 8, 4
    q, k, v = (rng.randn(b, h, s, d).astype(np.float32)
               for _ in range(3))
    keep = rng.rand(b * h, s, s) < 0.6
    keep |= np.eye(s, dtype=bool)[None]        # no empty rows
    # pattern as a sparse COO mask with dense shape [B*H, S, S]
    idx = np.stack(np.nonzero(keep))
    mask = sp.sparse_coo_tensor(idx, np.ones(idx.shape[1], np.float32),
                                keep.shape)
    out = F.attention(paddle.to_tensor(q), paddle.to_tensor(k),
                      paddle.to_tensor(v), mask).numpy()
    ref = _np_masked_attention(q, k, v, keep.reshape(b, h, s, s))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_sparse_attention_padding_and_attn_masks():
    rng = np.random.RandomState(8)
    b, h, s, d = 1, 2, 6, 4
    q, k, v = (rng.randn(b, h, s, d).astype(np.float32)
               for _ in range(3))
    full = np.ones((b * h, s, s), bool)
    idx = np.stack(np.nonzero(full))
    mask = sp.sparse_coo_tensor(idx, np.ones(idx.shape[1], np.float32),
                                full.shape)
    kp = np.ones((b, s), np.float32)
    kp[:, -2:] = 0                             # pad out last two keys
    am = np.tril(np.ones((s, s), np.float32))  # causal
    out = F.attention(paddle.to_tensor(q), paddle.to_tensor(k),
                      paddle.to_tensor(v), mask,
                      key_padding_mask=paddle.to_tensor(kp),
                      attn_mask=paddle.to_tensor(am)).numpy()
    keep = (full.reshape(b, h, s, s)
            & (kp != 0)[:, None, None, :]
            & (am != 0)[None, None])
    ref = _np_masked_attention(q, k, v, keep)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_layers_delegate_to_functional():
    xs, _ = _rand_sparse_ndhwc((1, 4, 4, 4, 2), density=0.4, seed=9)
    layer = sp.nn.SubmConv3D(2, 3, 3)
    out_layer = layer(xs).to_dense().numpy()
    out_fn = F.subm_conv3d(xs, layer.weight, layer.bias).to_dense().numpy()
    np.testing.assert_allclose(out_layer, out_fn)
