"""StringTensor + string kernels + FasterTokenizer (reference:
phi/core/string_tensor.h, phi/kernels/strings/*, faster_tokenizer_op.h
— the last SURVEY 2.1 'absent' row)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.string_tensor import (StringTensor, strings_empty,
                                           strings_lower, strings_upper)
from paddle_tpu.text import BasicTokenizer, FasterTokenizer


def test_string_tensor_basics():
    t = StringTensor([["ab", "cd"], ["ef", "GH"]])
    assert t.shape == [2, 2] and t.numel() == 4
    assert t.dtype == "pstring" and t.place == "cpu"
    assert t[1, 1] == "GH"
    row = t[0]
    assert isinstance(row, StringTensor) and row.tolist() == ["ab", "cd"]
    e = strings_empty([3])
    assert e.tolist() == ["", "", ""]
    c = strings_empty([2, 2]).copy_(t)
    assert c == t


def test_strings_case_kernels_unicode():
    t = StringTensor(["Hello", "ÀÉÎ", "Straße", "中文Mix"])
    low = strings_lower(t)
    assert low.tolist() == ["hello", "àéî", "straße", "中文mix"]
    up = strings_upper(t)
    assert up.tolist()[0] == "HELLO"
    assert up.tolist()[1] == "ÀÉÎ"
    # ascii-only mode leaves non-ascii untouched (reference non-utf8
    # path)
    low_ascii = strings_lower(StringTensor(["ÀBC"]),
                              use_utf8_encoding=False)
    assert low_ascii.tolist() == ["Àbc"]


def test_basic_tokenizer():
    bt = BasicTokenizer(do_lower_case=True)
    assert bt.tokenize("Hello, World!") == ["hello", ",", "world", "!"]
    # accents stripped, CJK split per char
    assert bt.tokenize("Café 中文") == ["cafe", "中", "文"]


VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "un", "##aff", "##able",
         "hello", "world", ",", "!", "the"]


def test_wordpiece_and_faster_tokenizer():
    tok = FasterTokenizer(VOCAB)
    ids, tt = tok("Hello, unaffable world!")
    v = {t: i for i, t in enumerate(VOCAB)}
    expect = [v["[CLS]"], v["hello"], v[","], v["un"], v["##aff"],
              v["##able"], v["world"], v["!"], v["[SEP]"]]
    np.testing.assert_array_equal(ids.numpy()[0], expect)
    assert ids.dtype.name in ("int64", "int32")
    # unknown word -> [UNK]
    ids2, _ = tok("zzz")
    assert v["[UNK]"] in ids2.numpy()[0]


def test_faster_tokenizer_pairs_padding_and_device_handoff():
    tok = FasterTokenizer(VOCAB)
    ids, tt = tok(["hello", "hello world"], text_pair=["world", "the"],
                  max_seq_len=8, pad_to_max_seq_len=True)
    assert ids.shape == [2, 8]
    # token_type marks the second segment
    assert tt.numpy()[0].max() == 1
    # ids feed straight into device-side embedding (the whole point)
    emb = paddle.nn.Embedding(len(VOCAB), 4)
    out = emb(ids)
    assert out.shape == [2, 8, 4]


def test_string_tensor_input_to_tokenizer():
    from paddle_tpu.core.string_tensor import StringTensor
    tok = FasterTokenizer(VOCAB)
    st = StringTensor(["hello world", "the un"])
    ids, _ = tok(st)
    assert ids.shape[0] == 2


def test_faster_tokenizer_longest_first_pair_truncation():
    """Pairwise truncation pops from the LONGER sequence (reference
    BertTokenizer::TruncateSequence, faster_tokenizer_op.cc:294) —
    the shorter side survives intact instead of both being tail-cut."""
    tok = FasterTokenizer(VOCAB)
    long_text = "hello world the un hello world the un"
    ids, tt = tok([long_text], text_pair=["un"], max_seq_len=8)
    row = ids.numpy()[0].tolist()
    # CLS + 4 first-seq tokens + SEP + "un" + SEP = exactly 8
    assert len(row) == 8
    assert row[0] == VOCAB.index("[CLS]")
    # the short pair ("un") must survive: exactly one token of type 1
    # before the final SEP
    t = tt.numpy()[0].tolist()
    assert sum(t) == 2            # "un" + its SEP carry type 1
    sep = tok.sep_id
    assert row[-1] == sep and row.count(sep) == 2


def test_faster_tokenizer_tiny_max_seq_len_no_crash():
    """max_seq_len below the special-token overhead must not crash
    (regression: longest-first truncation popped from empty lists)."""
    tok = FasterTokenizer(VOCAB)
    ids, tt = tok(["hello world the"], text_pair=["un"], max_seq_len=2)
    assert ids.shape[0] == 1
    assert ids.shape[1] <= 2          # hard length contract holds
    ids2, _ = tok(["hello world the"], max_seq_len=1)
    assert ids2.shape[0] == 1 and ids2.shape[1] <= 1
    # terminal-SEP contract survives the degenerate clamp: the last
    # kept token is rewritten to sep_id (legacy behavior)
    assert int(ids2.numpy()[0, -1]) == tok.sep_id
    ids3, _ = tok(["hello world the"], text_pair=["un"], max_seq_len=2)
    assert int(ids3.numpy()[0, -1]) == tok.sep_id
