"""StringTensor + string kernels + FasterTokenizer (reference:
phi/core/string_tensor.h, phi/kernels/strings/*, faster_tokenizer_op.h
— the last SURVEY 2.1 'absent' row)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.string_tensor import (StringTensor, strings_empty,
                                           strings_lower, strings_upper)
from paddle_tpu.text import BasicTokenizer, FasterTokenizer


def test_string_tensor_basics():
    t = StringTensor([["ab", "cd"], ["ef", "GH"]])
    assert t.shape == [2, 2] and t.numel() == 4
    assert t.dtype == "pstring" and t.place == "cpu"
    assert t[1, 1] == "GH"
    row = t[0]
    assert isinstance(row, StringTensor) and row.tolist() == ["ab", "cd"]
    e = strings_empty([3])
    assert e.tolist() == ["", "", ""]
    c = strings_empty([2, 2]).copy_(t)
    assert c == t


def test_strings_case_kernels_unicode():
    t = StringTensor(["Hello", "ÀÉÎ", "Straße", "中文Mix"])
    low = strings_lower(t)
    assert low.tolist() == ["hello", "àéî", "straße", "中文mix"]
    up = strings_upper(t)
    assert up.tolist()[0] == "HELLO"
    assert up.tolist()[1] == "ÀÉÎ"
    # ascii-only mode leaves non-ascii untouched (reference non-utf8
    # path)
    low_ascii = strings_lower(StringTensor(["ÀBC"]),
                              use_utf8_encoding=False)
    assert low_ascii.tolist() == ["Àbc"]


def test_basic_tokenizer():
    bt = BasicTokenizer(do_lower_case=True)
    assert bt.tokenize("Hello, World!") == ["hello", ",", "world", "!"]
    # accents stripped, CJK split per char
    assert bt.tokenize("Café 中文") == ["cafe", "中", "文"]


VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "un", "##aff", "##able",
         "hello", "world", ",", "!", "the"]


def test_wordpiece_and_faster_tokenizer():
    tok = FasterTokenizer(VOCAB)
    ids, tt = tok("Hello, unaffable world!")
    v = {t: i for i, t in enumerate(VOCAB)}
    expect = [v["[CLS]"], v["hello"], v[","], v["un"], v["##aff"],
              v["##able"], v["world"], v["!"], v["[SEP]"]]
    np.testing.assert_array_equal(ids.numpy()[0], expect)
    assert ids.dtype.name in ("int64", "int32")
    # unknown word -> [UNK]
    ids2, _ = tok("zzz")
    assert v["[UNK]"] in ids2.numpy()[0]


def test_faster_tokenizer_pairs_padding_and_device_handoff():
    tok = FasterTokenizer(VOCAB)
    ids, tt = tok(["hello", "hello world"], text_pair=["world", "the"],
                  max_seq_len=8, pad_to_max_seq_len=True)
    assert ids.shape == [2, 8]
    # token_type marks the second segment
    assert tt.numpy()[0].max() == 1
    # ids feed straight into device-side embedding (the whole point)
    emb = paddle.nn.Embedding(len(VOCAB), 4)
    out = emb(ids)
    assert out.shape == [2, 8, 4]


def test_string_tensor_input_to_tokenizer():
    from paddle_tpu.core.string_tensor import StringTensor
    tok = FasterTokenizer(VOCAB)
    st = StringTensor(["hello world", "the un"])
    ids, _ = tok(st)
    assert ids.shape[0] == 2
