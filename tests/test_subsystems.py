"""Tests: distribution, sparse, geometric, device, incubate, quantization,
inference, custom ops, watchdog, elastic, auto_tuner."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def test_distribution_normal():
    from paddle_tpu.distribution import Normal, kl_divergence
    d = Normal(0.0, 1.0)
    s = d.sample([1000])
    assert abs(float(s.mean())) < 0.15
    lp = d.log_prob(paddle.to_tensor(0.0))
    np.testing.assert_allclose(float(lp), -0.9189385, rtol=1e-5)
    q = Normal(1.0, 2.0)
    kl = kl_divergence(d, q)
    # closed form: log(2) + (1+1)/8 - 1/2
    np.testing.assert_allclose(float(kl), np.log(2) + 2 / 8 - 0.5,
                               rtol=1e-5)


def test_distribution_rsample_grad():
    from paddle_tpu.distribution import Normal
    loc = paddle.to_tensor(0.5, stop_gradient=False)
    scale = paddle.to_tensor(1.5, stop_gradient=False)
    d = Normal(loc, scale)
    s = d.rsample([64])
    s.mean().backward()
    assert loc.grad is not None and abs(float(loc.grad) - 1.0) < 1e-5


def test_distribution_categorical_bernoulli():
    from paddle_tpu.distribution import Bernoulli, Categorical
    c = Categorical(logits=paddle.to_tensor([0.0, 0.0, 10.0]))
    s = c.sample([100])
    assert float((s == 2).astype("float32").mean()) > 0.95
    ent = c.entropy()
    assert float(ent) < 0.1
    b = Bernoulli(probs=paddle.to_tensor(0.8))
    np.testing.assert_allclose(float(b.log_prob(paddle.to_tensor(1.0))),
                               np.log(0.8), rtol=1e-5)


def test_sparse_coo():
    import paddle_tpu.sparse as sparse
    idx = [[0, 1, 2], [1, 2, 0]]
    vals = [1.0, 2.0, 3.0]
    s = sparse.sparse_coo_tensor(idx, vals, shape=[3, 3])
    dense = s.to_dense().numpy()
    assert dense[0, 1] == 1 and dense[1, 2] == 2 and dense[2, 0] == 3
    assert s.nnz() == 3
    y = sparse.matmul(s, paddle.eye(3))
    np.testing.assert_allclose(y.numpy(), dense)
    s2 = sparse.to_sparse_coo(paddle.to_tensor(dense))
    np.testing.assert_allclose(s2.to_dense().numpy(), dense)


def test_geometric_segment_ops():
    import paddle_tpu.geometric as G
    data = paddle.to_tensor([[1.0], [2.0], [3.0], [4.0]])
    ids = paddle.to_tensor([0, 0, 1, 1])
    np.testing.assert_allclose(G.segment_sum(data, ids).numpy(),
                               [[3.0], [7.0]])
    np.testing.assert_allclose(G.segment_mean(data, ids).numpy(),
                               [[1.5], [3.5]])
    np.testing.assert_allclose(G.segment_max(data, ids).numpy(),
                               [[2.0], [4.0]])
    x = paddle.to_tensor([[1.0, 0.0], [0.0, 1.0], [2.0, 2.0]])
    src = paddle.to_tensor([0, 1, 1])
    dst = paddle.to_tensor([1, 0, 2])
    out = G.send_u_recv(x, src, dst, "sum")
    np.testing.assert_allclose(out.numpy(),
                               [[0, 1], [1, 0], [0, 1]])


def test_device_api():
    import paddle_tpu.device as device
    assert device.device_count() >= 1
    assert isinstance(device.get_available_device(), list)
    device.synchronize()
    assert device.memory_allocated() >= 0


def test_incubate_fused_ops():
    import paddle_tpu.incubate.nn.functional as IF
    x = paddle.randn([2, 4, 8], dtype="float32")
    w = paddle.ones([8])
    out, _ = IF.fused_rms_norm(x, w)
    ref = x.numpy() / np.sqrt(
        (x.numpy() ** 2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)
    y = IF.swiglu(paddle.randn([2, 8]))
    assert y.shape == [2, 4]
    q = paddle.randn([1, 4, 2, 8])
    k = paddle.randn([1, 4, 2, 8])
    q2, k2, _ = IF.fused_rotary_position_embedding(q, k)
    assert q2.shape == q.shape and k2.shape == k.shape


def test_quantization_qat_and_ptq():
    from paddle_tpu.quantization import PTQ, QAT, QuantConfig
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 4))
    x = paddle.randn([4, 8])
    ref = m(x)
    qat_model = QAT(QuantConfig()).quantize(m, inplace=False)
    from paddle_tpu.quantization import QuantedLinear
    # inplace=False: original model untouched
    assert not isinstance(m[0], QuantedLinear)
    assert isinstance(qat_model[0], QuantedLinear)
    out = qat_model(x)
    assert out.shape == [4, 4]
    # quantized forward should be close-ish but not exact
    assert np.abs(out.numpy() - ref.numpy()).max() < 1.0
    # QAT still trains
    loss = out.sum()
    loss.backward()
    assert qat_model[0].inner.weight.grad is not None

    m2 = nn.Sequential(nn.Linear(8, 4))
    ptq = PTQ(QuantConfig())
    ptq.quantize(m2, inplace=True)
    for _ in range(3):
        m2(paddle.randn([4, 8]))
    m2q = ptq.convert(m2, inplace=False)
    assert not isinstance(m2[0], QuantedLinear)  # original preserved
    assert isinstance(m2q[0], QuantedLinear)
    out2 = m2q(x)
    assert out2.shape == [4, 4]
    # int8 PTQ on a small net stays close to fp32
    assert np.abs(out2.numpy() - m2(x).numpy()).max() < 0.5


def test_inference_predictor():
    from paddle_tpu.inference import Config, create_predictor
    m = nn.Linear(4, 2)
    cfg = Config()
    cfg.set_layer(m)
    pred = create_predictor(cfg)
    x = paddle.randn([3, 4])
    (out,) = pred.run([x])
    np.testing.assert_allclose(out.numpy(), m(x).numpy(), rtol=1e-5)


def test_custom_op_with_grad():
    from paddle_tpu.utils.cpp_extension import register_op
    import jax.numpy as jnp
    op = register_op(
        "my_double",
        forward=lambda x: x * 2.0,
        backward=lambda x, g: g * 2.0)
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = op(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_cpp_extension_load(tmp_path):
    from paddle_tpu.utils.cpp_extension import load
    src = tmp_path / "ext.cc"
    src.write_text(
        'extern "C" long long addll(long long a, long long b) '
        "{ return a + b; }\n")
    lib = load("testext", [str(src)], build_directory=str(tmp_path))
    import ctypes
    lib.addll.restype = ctypes.c_longlong
    assert lib.addll(ctypes.c_longlong(40), ctypes.c_longlong(2)) == 42


def test_watchdog_healthy():
    from paddle_tpu.distributed.watchdog import CollectiveWatchdog
    wd = CollectiveWatchdog(timeout_s=30, interval_s=0.05)
    wd.start()
    import time
    time.sleep(0.3)
    wd.stop()
    assert not wd.tripped


def test_elastic_membership(tmp_path):
    from paddle_tpu.distributed.elastic import ElasticManager, FileKVStore
    store = FileKVStore(str(tmp_path))
    changes = []
    m0 = ElasticManager(store, "job1", 0, heartbeat_s=0.05, ttl_s=0.5,
                        on_change=lambda w: changes.append(list(w)))
    m1 = ElasticManager(store, "job1", 1, heartbeat_s=0.05, ttl_s=0.5)
    m0.start()
    m1.start()
    import time
    time.sleep(0.3)
    assert m0.world() == [0, 1]
    m1.stop()
    time.sleep(1.0)
    assert m0.world() == [0]
    assert changes and changes[-1] == [0]
    m0.stop()


def test_auto_tuner():
    from paddle_tpu.distributed.auto_tuner import (Candidate,
                                                   generate_candidates,
                                                   prune_by_memory, tune)
    cands = generate_candidates(8, num_layers=4, global_batch=16,
                                num_heads=8)
    assert all(c.dp * c.pp * c.tp == 8 for c in cands)
    assert any(c.pp > 1 for c in cands)
    pruned = prune_by_memory(cands, param_bytes=10 * 2 ** 30,
                             hbm_bytes=16 * 2 ** 30, optimizer_mult=4)
    assert all(c.tp * c.pp >= 4 for c in pruned)

    def fake_run(c):
        if c.tp == 8:
            raise RuntimeError("oom")
        return 1.0 / (c.dp + 0.5 * c.tp)

    best = tune(fake_run, cands, verbose=False)
    assert best.error is None and best.time_s is not None


def test_unique_name_and_run_check():
    from paddle_tpu.utils import run_check, unique_name
    a = unique_name.generate("fc")
    b = unique_name.generate("fc")
    assert a != b
    with unique_name.guard():
        c = unique_name.generate("fc")
        assert c == "fc_0"
    assert run_check()


def test_dist_checkpoint_load_is_shard_wise(tmp_path):
    """VERDICT r2 item 6: loading a sharded tensor must not materialize
    the global array on host — peak host allocation stays O(shard)."""
    import tracemalloc
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P, Mesh
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed import checkpoint as dc

    n = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()), ("x",))
    sh = NamedSharding(mesh, P("x", None))
    global_shape = (1024 * n, 512)            # n=8: 16 MB fp32 global
    global_bytes = int(np.prod(global_shape)) * 4
    big = jax.device_put(
        jax.numpy.zeros(global_shape, "float32") + 3.25, sh)
    t = Tensor.__new__(Tensor)
    t._init_from_array(big)
    state = {"w": t}
    dc.save_state_dict(state, str(tmp_path / "ckpt"))

    target = Tensor.__new__(Tensor)
    target._init_from_array(jax.device_put(
        jax.numpy.zeros(global_shape, "float32"), sh))
    state2 = {"w": target}
    # spy on host staging: the largest single buffer the loader
    # allocates must be shard-sized, never the global array (the old
    # path's np.zeros(global_shape)). Total-peak is not meaningful on
    # the CPU backend, where the target's device storage aliases host
    # RAM by definition.
    staged = []
    orig_zeros = dc.np.zeros

    def spy_zeros(shape, *a, **k):
        arr = orig_zeros(shape, *a, **k)
        staged.append(arr.nbytes)
        return arr

    tracemalloc.start()
    dc.np.zeros = spy_zeros
    try:
        dc.load_state_dict(state2, str(tmp_path / "ckpt"))
    finally:
        dc.np.zeros = orig_zeros
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    np.testing.assert_allclose(np.asarray(target._data[:4, :4]), 3.25)
    shard_bytes = global_bytes // n
    assert staged and max(staged) <= shard_bytes, (staged, shard_bytes)
    # and the traced transient peak stays bounded by the (aliased)
    # device storage plus O(shard) staging — not 2x global
    assert peak < global_bytes + 4 * shard_bytes, (peak, global_bytes)


def test_dist_checkpoint_cross_mesh_block_reshard(tmp_path):
    """Save sharded over 8, load sharded over a DIFFERENT axis layout:
    per-shard assembly must stitch intersecting source entries."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P, Mesh
    import numpy as np
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed import checkpoint as dc

    devs = jax.devices()
    mesh8 = Mesh(np.array(devs), ("x",))
    rng = np.random.default_rng(0)
    val = rng.standard_normal((16, 12)).astype("float32")
    src = Tensor.__new__(Tensor)
    src._init_from_array(jax.device_put(
        jax.numpy.asarray(val), NamedSharding(mesh8, P("x", None))))
    dc.save_state_dict({"w": src}, str(tmp_path / "ck2"))

    mesh24 = Mesh(np.array(devs).reshape(2, 4), ("a", "b"))
    tgt = Tensor.__new__(Tensor)
    tgt._init_from_array(jax.device_put(
        jax.numpy.zeros((16, 12), "float32"),
        NamedSharding(mesh24, P("b", "a"))))
    dc.load_state_dict({"w": tgt}, str(tmp_path / "ck2"))
    np.testing.assert_allclose(np.asarray(tgt._data), val)
