"""Symbolic/dynamic-shape training surface (VERDICT r3 item 6;
reference: PIR shape dialect + InputSpec(-1) dims,
/root/reference/paddle/pir/include/dialect/shape): InputSpec None dims
on to_static fns give a tracked, capped family of exact-shape
executables for training, and padded power-of-two buckets (ONE
executable) for row-independent inference fns."""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.jit import InputSpec


def _train_setup():
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    opt = paddle.optimizer.SGD(0.05, parameters=m.parameters())
    lossf = nn.MSELoss()

    def step(x, y):
        loss = lossf(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss
    return m, opt, step


def test_train_step_serves_two_batch_sizes_bounded():
    m, opt, step = _train_setup()
    st = paddle.jit.to_static(
        step, objs=[m, opt],
        input_spec=[InputSpec([None, 8]), InputSpec([None, 4])])
    rng = np.random.RandomState(0)
    for b in (4, 6, 4, 6):
        x = paddle.to_tensor(rng.randn(b, 8).astype("f4"))
        y = paddle.to_tensor(rng.randn(b, 4).astype("f4"))
        st(x, y)
    rep = st.report()
    assert sorted(rep["shape_specializations"]) == [(4, 4), (6, 6)]
    assert not rep["shape_overflowed"]
    # exact numerics: replay the same schedule eagerly
    m2, opt2, step2 = _train_setup()
    rng = np.random.RandomState(0)
    for b in (4, 6, 4, 6):
        x = paddle.to_tensor(rng.randn(b, 8).astype("f4"))
        y = paddle.to_tensor(rng.randn(b, 4).astype("f4"))
        step2(x, y)
    for (_, a), (_, b_) in zip(m.named_parameters(),
                               m2.named_parameters()):
        np.testing.assert_allclose(a.numpy(), b_.numpy(), rtol=1e-5,
                                   atol=1e-6)


def test_shape_cap_falls_back_to_eager():
    from paddle_tpu.core.flags import get_flag, set_flags
    m, opt, step = _train_setup()
    st = paddle.jit.to_static(
        step, objs=[m, opt],
        input_spec=[InputSpec([None, 8]), InputSpec([None, 4])])
    old = get_flag("FLAGS_max_shape_specializations")
    set_flags({"FLAGS_max_shape_specializations": 2})
    try:
        rng = np.random.RandomState(0)
        for b in (2, 3):
            st(paddle.to_tensor(rng.randn(b, 8).astype("f4")),
               paddle.to_tensor(rng.randn(b, 4).astype("f4")))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            loss = st(paddle.to_tensor(rng.randn(5, 8).astype("f4")),
                      paddle.to_tensor(rng.randn(5, 4).astype("f4")))
        assert any("dynamic shapes" in str(x.message) for x in w), \
            [str(x.message) for x in w]
        assert np.isfinite(float(loss))          # eager still trains
        assert len(st.report()["shape_specializations"]) == 2
        assert st.report()["shape_overflowed"]
    finally:
        set_flags({"FLAGS_max_shape_specializations": old})


def test_padded_buckets_one_executable_exact_rows():
    paddle.seed(1)
    m = nn.Linear(8, 4)
    m.eval()

    def fwd(x):
        return m(x)

    st = paddle.jit.to_static(
        fwd, objs=[m], input_spec=[InputSpec([None, 8])],
        pad_dynamic_dims=True)
    rng = np.random.RandomState(1)
    outs = {}
    for b in (3, 4, 2):
        x = paddle.to_tensor(rng.randn(b, 8).astype("f4"))
        out = st(x)
        assert out.shape == [b, 4]
        np.testing.assert_allclose(out.numpy(), m(x).numpy(),
                                   rtol=1e-6, atol=1e-6)
        outs[b] = out
    # one executable serves buckets: 3 and 2 pad to 4's bucket / 2's?
    # buckets are next-pow2: 3->4, 4->4, 2->2 — at most TWO programs,
    # not three, and repeated sizes never recompile
    entry = next(iter(st._cache.values()))
    assert entry["specs"][0].jitted._cache_size() <= 2


def test_pad_mode_refuses_stateful_train():
    m, opt, step = _train_setup()
    with pytest.raises(ValueError, match="corrupt stateful"):
        paddle.jit.to_static(
            step, objs=[m, opt],
            input_spec=[InputSpec([None, 8]), InputSpec([None, 4])],
            pad_dynamic_dims=True)


def test_pad_mode_spares_batch_independent_outputs():
    """The eval_shape slice plan must NOT truncate outputs that merely
    coincide with the bucket size on axis 0 (review finding)."""
    paddle.seed(2)
    m = nn.Linear(8, 4)
    m.eval()

    def fwd(x):
        # second output is batch-independent [8, 8] — equal to batch
        # 5's bucket size — and must come back intact
        return m(x), paddle.ones([8, 8])

    st = paddle.jit.to_static(fwd, objs=[m],
                              input_spec=[InputSpec([None, 8])],
                              pad_dynamic_dims=True)
    x = paddle.to_tensor(np.random.RandomState(0).randn(5, 8)
                         .astype("f4"))
    out, const = st(x)
    assert out.shape == [5, 4]
    assert const.shape == [8, 8], const.shape
    np.testing.assert_allclose(out.numpy(), m(x).numpy(), rtol=1e-6,
                               atol=1e-6)


def test_rank_mismatch_raises_clear_error():
    def fwd(x):
        return x * 2
    st = paddle.jit.to_static(fwd, input_spec=[InputSpec([None, None])])
    with pytest.raises(ValueError, match="dynamic dim 1"):
        st(paddle.to_tensor(np.zeros((3,), "f4")))


def test_pad_mask_bucketed_train_matches_unpadded():
    """Bucketed dynamic-shape TRAINING (round 5; reference: the PIR
    shape dialect serves training compilation, 377 ops with
    InferSymbolicShapeInterface): pad_mask_arg lifts the stateful-objs
    refusal — the injected mask zero-weights pad positions, so one
    executable serves a whole bucket of sequence lengths with grads,
    optimizer state and loss matching the exact unpadded runs. Compile
    events are counted with the framework's own compile-cache tracker
    (observability.count_compiles — the jtu counter API drifted),
    asserting steady state compiles NOTHING new."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu import observability as obs

    def setup():
        paddle.seed(5)
        m = LlamaForCausalLM(LlamaConfig.tiny())
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=m.parameters())

        def step(x, y, seq_mask):
            logits = m(x)                          # [B, S, V] causal
            v = logits.shape[-1]
            ce = F.cross_entropy(logits.reshape([-1, v]),
                                 y.reshape([-1]),
                                 reduction="none").reshape(x.shape)
            w = paddle.broadcast_to(seq_mask.unsqueeze(0), x.shape)
            loss = (ce * w).sum() / w.sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        return m, opt, step

    rng = np.random.RandomState(9)
    lengths = (44, 57, 62, 51)                     # one 64 bucket
    batches = [rng.randint(0, 256, (2, s)).astype("int64")
               for s in lengths]

    # ---- bucketed run ------------------------------------------------
    m, opt, step = setup()
    st = paddle.jit.to_static(
        step, objs=[m, opt],
        input_spec=[InputSpec([2, None], "int64"),
                    InputSpec([2, None], "int64")],
        pad_dynamic_dims=True, pad_mask_arg="seq_mask")
    losses = []
    losses.append(float(st(paddle.to_tensor(batches[0]),
                           paddle.to_tensor(batches[0]))))
    with obs.count_compiles() as compiles:
        for b in batches[1:]:
            losses.append(float(st(paddle.to_tensor(b),
                                   paddle.to_tensor(b))))
    assert compiles() == 0, (
        f"steady-state bucketed train recompiled {compiles()} times "
        f"for lengths {lengths[1:]}")

    # ---- exact unpadded oracle --------------------------------------
    m2, opt2, step2 = setup()
    for i, b in enumerate(batches):
        x = paddle.to_tensor(b)
        mask = paddle.to_tensor(np.ones(b.shape[1], np.float32))
        ref_loss = float(step2(x, x, mask))
        np.testing.assert_allclose(losses[i], ref_loss, rtol=2e-4,
                                   err_msg=f"loss step {i}")
    for (_, a), (_, c) in zip(m.named_parameters(),
                              m2.named_parameters()):
        # rtol calibrated for CPU XLA: after 4 AdamW steps the padded
        # compiled run and the eager unpadded oracle accumulate ~1e-3
        # relative drift on isolated weight elements (reduction-order
        # float noise, not a masking leak — the per-step losses above
        # already match at 2e-4)
        np.testing.assert_allclose(a.numpy(), c.numpy(), rtol=2e-3,
                                   atol=3e-5)
