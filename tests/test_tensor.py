"""Tensor basics: creation, meta, dunders, indexing, inplace."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_and_meta():
    t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == [2, 2]
    assert t.dtype == paddle.float32
    assert t.ndim == 2
    assert t.size == 4
    np.testing.assert_allclose(t.numpy(), [[1, 2], [3, 4]])


def test_dtype_inference():
    assert paddle.to_tensor([1, 2]).dtype == np.dtype(np.int32) or \
        paddle.to_tensor([1, 2]).dtype == np.dtype(np.int64)
    assert paddle.to_tensor(1.5).dtype == paddle.float32
    assert paddle.to_tensor(True).dtype == paddle.bool


def test_arithmetic_dunders():
    x = paddle.to_tensor([1.0, 2.0, 3.0])
    y = paddle.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose((x + y).numpy(), [5, 7, 9])
    np.testing.assert_allclose((x - y).numpy(), [-3, -3, -3])
    np.testing.assert_allclose((x * y).numpy(), [4, 10, 18])
    np.testing.assert_allclose((y / x).numpy(), [4, 2.5, 2])
    np.testing.assert_allclose((x ** 2).numpy(), [1, 4, 9])
    np.testing.assert_allclose((-x).numpy(), [-1, -2, -3])
    np.testing.assert_allclose((1 + x).numpy(), [2, 3, 4])
    np.testing.assert_allclose((x @ y).numpy(), 32.0)


def test_scalar_keeps_dtype():
    x = paddle.to_tensor([1.0, 2.0], dtype="bfloat16")
    assert (x + 1).dtype == paddle.bfloat16
    assert (x * 2.0).dtype == paddle.bfloat16


def test_promotion():
    a = paddle.to_tensor([1], dtype="int32")
    b = paddle.to_tensor([1.0], dtype="float32")
    assert (a + b).dtype == paddle.float32


def test_comparison():
    x = paddle.to_tensor([1.0, 2.0, 3.0])
    np.testing.assert_array_equal((x > 1.5).numpy(), [False, True, True])
    np.testing.assert_array_equal((x == 2.0).numpy(), [False, True, False])


def test_indexing():
    x = paddle.to_tensor(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    np.testing.assert_allclose(x[0, 1].numpy(), np.arange(4, 8))
    np.testing.assert_allclose(x[:, -1, ::2].numpy(),
                               np.arange(24).reshape(2, 3, 4)[:, -1, ::2])
    idx = paddle.to_tensor([0, 1])
    np.testing.assert_allclose(x[idx, idx].numpy(),
                               np.arange(24).reshape(2, 3, 4)[[0, 1], [0, 1]])


def test_setitem():
    x = paddle.zeros([3, 3])
    x[1] = 5.0
    assert x.numpy()[1].tolist() == [5, 5, 5]
    x[0, 0] = paddle.to_tensor(7.0)
    assert x.numpy()[0, 0] == 7
    assert x.inplace_version() >= 2


def test_inplace_math():
    x = paddle.to_tensor([1.0, 2.0])
    x.add_(paddle.to_tensor([1.0, 1.0]))
    np.testing.assert_allclose(x.numpy(), [2, 3])
    x.scale_(2.0)
    np.testing.assert_allclose(x.numpy(), [4, 6])


def test_cast_and_astype():
    x = paddle.to_tensor([1.5, 2.5])
    y = x.astype("int32")
    assert y.dtype == paddle.int32
    z = x.cast("bfloat16")
    assert z.dtype == paddle.bfloat16


def test_reshape_transpose_methods():
    x = paddle.arange(6, dtype="float32")
    y = x.reshape([2, 3])
    assert y.shape == [2, 3]
    assert y.T.shape == [3, 2]
    assert x.unsqueeze(0).shape == [1, 6]
    assert y.flatten().shape == [6]


def test_clone_detach():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    d = x.detach()
    assert d.stop_gradient
    c = x.clone()
    assert not c.stop_gradient


def test_item_and_float():
    x = paddle.to_tensor(3.5)
    assert x.item() == 3.5
    assert float(x) == 3.5
    assert int(paddle.to_tensor(3)) == 3


def test_save_load(tmp_path):
    x = paddle.to_tensor([[1.0, 2.0]], dtype="bfloat16")
    state = {"w": x, "step": 3}
    p = str(tmp_path / "ckpt.pdparams")
    paddle.save(state, p)
    loaded = paddle.load(p)
    assert loaded["step"] == 3
    assert loaded["w"].dtype == paddle.bfloat16
    np.testing.assert_allclose(loaded["w"].astype("float32").numpy(),
                               [[1, 2]])
