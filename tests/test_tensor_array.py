"""Tensor-array API (reference python/paddle/tensor/array.py:43,110,
206,308): list semantics in eager mode, fixed-capacity
StaticTensorArray lowering (dynamic_update_slice-backed) under traces,
and a dy2static while-loop accumulating into an array."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_eager_list_semantics_match_reference():
    arr = paddle.tensor.create_array(dtype="float32")
    assert arr == []
    x = paddle.full([1, 3], 5, dtype="float32")
    i = paddle.zeros([1], dtype="int32")
    arr = paddle.tensor.array_write(x, i, array=arr)
    item = paddle.tensor.array_read(arr, i)
    np.testing.assert_array_equal(item.numpy(), np.full((1, 3), 5.0))
    n = paddle.tensor.array_length(arr)
    assert n.shape == [] and int(n) == 1
    # i == len appends; i < len overwrites; i > len raises
    arr = paddle.tensor.array_write(x * 2, paddle.to_tensor([1]), arr)
    assert int(paddle.tensor.array_length(arr)) == 2
    arr = paddle.tensor.array_write(x * 3, paddle.to_tensor([0]), arr)
    np.testing.assert_array_equal(
        paddle.tensor.array_read(arr, paddle.to_tensor([0])).numpy(),
        np.full((1, 3), 15.0))
    with pytest.raises(IndexError):
        paddle.tensor.array_write(x, paddle.to_tensor([5]), arr)


def test_array_write_creates_array_when_none():
    x = paddle.ones([2])
    arr = paddle.tensor.array_write(x, paddle.zeros([1], "int64"))
    assert isinstance(arr, list) and len(arr) == 1


def test_initialized_list():
    x = paddle.ones([2, 2])
    arr = paddle.tensor.create_array("float32", initialized_list=[x])
    assert int(paddle.tensor.array_length(arr)) == 1
    with pytest.raises(TypeError):
        paddle.tensor.create_array("float32", initialized_list=[1.0])


def test_static_array_read_write_parity_with_list():
    xs = [paddle.to_tensor(np.random.RandomState(s).randn(3)
                           .astype("float32")) for s in range(4)]
    lst = paddle.tensor.create_array("float32")
    sta = paddle.tensor.create_array("float32", capacity=8,
                                     element_shape=[3])
    for j, x in enumerate(xs):
        i = paddle.to_tensor([j])
        lst = paddle.tensor.array_write(x, i, lst)
        sta = paddle.tensor.array_write(x, i, sta)
    assert int(paddle.tensor.array_length(sta)) == 4
    for j in range(4):
        i = paddle.to_tensor([j])
        np.testing.assert_array_equal(
            paddle.tensor.array_read(lst, i).numpy(),
            paddle.tensor.array_read(sta, i).numpy())


def test_traced_index_on_list_raises_with_guidance():
    lst = [paddle.ones([2])]

    def f(i):
        return paddle.tensor.array_read(lst, i)

    st = paddle.jit.to_static(f)
    with pytest.raises(TypeError, match="capacity"):
        st(paddle.to_tensor([0]))


def test_dy2static_while_loop_accumulates_into_array():
    """The dy2static while-loop-carried-array case the reference routes
    through LOD_TENSOR_ARRAY: cumulative sums collected into a
    fixed-capacity array inside ONE compiled program."""
    def fn(x, n_steps):
        arr = paddle.tensor.create_array("float32", capacity=8,
                                         element_shape=[3])
        i = paddle.zeros([], "int64")
        total = paddle.zeros([3], "float32")

        def cond(i, total, arr):
            return i < n_steps

        def body(i, total, arr):
            total = total + x
            arr = paddle.tensor.array_write(total, i, arr)
            return i + 1, total, arr

        i, total, arr = paddle.static.nn.while_loop(
            cond, body, [i, total, arr])
        return paddle.tensor.array_read(arr, n_steps - 1), \
            paddle.tensor.array_length(arr)

    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
    st = paddle.jit.to_static(fn)
    last, n = st(x, paddle.to_tensor(4, "int64"))
    np.testing.assert_allclose(last.numpy(), [4.0, 8.0, 12.0])
    assert int(n) == 4
    # a different trip count reuses the SAME executable (the count is
    # an operand of the while_loop, not a shape)
    last2, n2 = st(x, paddle.to_tensor(6, "int64"))
    np.testing.assert_allclose(last2.numpy(), [6.0, 12.0, 18.0])
    assert int(n2) == 6
