"""Training under fire (ISSUE 15): step guards with skip-step +
circuit-breaker semantics, per-step stall watchdog with straggler
attribution, preemption-safe checkpointing with exact resume, and the
run_resilient crash-resume supervisor — every path driven by the
paddle_tpu._chaos training hook sites.

Everything here is a tiny eager MLP on CPU; the chaos-marked tests
carry the `chaos` marker (pytest.ini) and the whole module stays well
under the tier-1 budget."""
import os
import signal
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.observability as obs
from paddle_tpu import _chaos
from paddle_tpu import io as pio
from paddle_tpu import nn
from paddle_tpu.amp import GradScaler
from paddle_tpu.distributed import checkpoint as dc
from paddle_tpu.distributed.elastic import FileKVStore, run_resilient
from paddle_tpu.distributed.watchdog import (TrainHangError,
                                             TrainStepWatchdog)
from paddle_tpu.hapi import Callback, FaultTolerantCheckpoint, Model
from paddle_tpu.training import (NonFiniteStepError, PreemptionHandler,
                                 StepGuard, load_train_checkpoint,
                                 save_train_checkpoint)


@pytest.fixture(autouse=True)
def _metrics_on():
    obs.enable()
    obs.REGISTRY.reset()
    yield
    obs.enable()


#: dataset item loads recorded here — the data-order oracle
_SERVED = []


class _RecData(pio.Dataset):
    def __len__(self):
        return 32

    def __getitem__(self, i):
        _SERVED.append(i)
        r = np.random.RandomState(i)
        return (r.randn(4).astype("f4"), r.randn(2).astype("f4"))


def _build(seed=123):
    paddle.seed(7)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    model = Model(net)
    model.prepare(paddle.optimizer.SGD(0.1, parameters=net.parameters()),
                  nn.MSELoss())
    loader = pio.DataLoader(_RecData(), batch_size=4, shuffle=True,
                            seed=seed)
    return model, loader


def _params(model):
    return {k: v.numpy().copy()
            for k, v in model.network.state_dict().items()}


def _arm():
    os.environ[_chaos.ENV] = "on"
    _chaos.clear()


# ------------------------------------------------------------ step guards
def test_step_guard_skips_nonfinite_step_and_ticks_counters():
    model, _ = _build()
    guard = StepGuard(max_consecutive_bad=3)
    model._step_guard = guard
    bad = paddle.to_tensor(np.full((4, 4), np.inf, "f4"))
    good = paddle.to_tensor(np.random.randn(4, 4).astype("f4"))
    y = paddle.to_tensor(np.zeros((4, 2), "f4"))

    before = _params(model)
    out = model.train_batch(bad, y)
    after = _params(model)
    # the update was SKIPPED: parameters untouched, run alive
    for k in before:
        np.testing.assert_array_equal(before[k], after[k])
    assert not np.isfinite(out[0])
    assert guard.nan_steps == 1 and guard.skipped_steps == 1
    assert guard.consecutive_bad == 1
    assert obs.counter("train.nan_steps").value == 1
    assert obs.counter("train.skipped_steps").value == 1

    # a good step applies the update and resets the breaker window
    model.train_batch(good, y)
    assert guard.consecutive_bad == 0
    changed = _params(model)
    assert any(not np.array_equal(after[k], changed[k]) for k in after)


def test_step_guard_circuit_breaker_aborts_with_diagnostic():
    model, _ = _build()
    model._step_guard = StepGuard(max_consecutive_bad=2)
    bad = paddle.to_tensor(np.full((4, 4), np.inf, "f4"))
    y = paddle.to_tensor(np.zeros((4, 2), "f4"))
    model.train_batch(bad, y)                      # bad #1: skipped
    with pytest.raises(NonFiniteStepError) as ei:  # bad #2: abort
        model.train_batch(bad, y)
    msg = str(ei.value)
    assert "2 consecutive" in msg and "garbage" in msg
    assert obs.counter("train.nan_steps").value == 2


def test_step_guard_checks_grads_when_asked():
    model, _ = _build()
    guard = StepGuard(max_consecutive_bad=5, check_grads=True)
    x = paddle.to_tensor(np.random.randn(4, 4).astype("f4"))
    y = paddle.to_tensor(np.zeros((4, 2), "f4"))
    # materialize grads, then poison ONE grad while the loss is finite
    model.train_batch(x, y)
    loss = nn.MSELoss()(model.network(x), y)
    loss.backward()
    p = model.network.parameters()[0]
    import jax.numpy as jnp
    p.grad._assign_array(jnp.full(p.grad._data.shape, jnp.inf,
                                  p.grad._data.dtype))
    assert not guard.pre_step(loss, model._optimizer)
    assert guard.nan_steps == 1


def test_step_guard_is_amp_scaler_aware():
    paddle.seed(0)
    net = nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
    scaler = GradScaler(enable=True, init_loss_scaling=8.0)
    guard = StepGuard(max_consecutive_bad=2)
    x = paddle.to_tensor(np.random.randn(4, 4).astype("f4"))

    loss = net(x).mean()
    scaler.scale(loss).backward()
    import jax.numpy as jnp
    p = net.parameters()[0]
    p.grad._assign_array(jnp.full(p.grad._data.shape, jnp.inf,
                                  p.grad._data.dtype))
    scaler.step(opt)                      # scaler skips the update
    assert scaler.last_step_skipped()
    assert not guard.observe_scaler(scaler)
    # scaler-managed skip: counted as skipped, NOT as a NaN detection
    assert guard.skipped_steps == 1 and guard.nan_steps == 0
    assert obs.counter("train.skipped_steps").value == 1
    scaler.update()
    opt.clear_grad()

    # a clean scaled step resets the breaker window
    loss = net(x).mean()
    scaler.scale(loss).backward()
    scaler.step(opt)
    assert not scaler.last_step_skipped()
    assert guard.observe_scaler(scaler)
    assert guard.consecutive_bad == 0


# ---------------------------------------------------------- hang detection
@pytest.mark.chaos
def test_step_watchdog_aborts_hung_step():
    _arm()
    _chaos.install("train.step", kind="slow", seconds=5.0, times=1)
    model, _ = _build()
    wd = TrainStepWatchdog(timeout_s=0.2, interval_s=0.03)
    model._watchdog = wd
    x = paddle.to_tensor(np.random.randn(4, 4).astype("f4"))
    y = paddle.to_tensor(np.zeros((4, 2), "f4"))
    t0 = time.perf_counter()
    try:
        with pytest.raises(TrainHangError, match="stalled"):
            model.train_batch(x, y)
    finally:
        wd.stop()
    # the abort is PROMPT (the 5s injected stall was interrupted),
    # and never a silent hang
    assert time.perf_counter() - t0 < 3.0
    assert wd.tripped
    assert obs.counter("train.hang_aborts").value == 1


def test_step_watchdog_names_stragglers(tmp_path):
    """Cross-rank attribution: rank 0 publishes progress, rank 1 never
    does — the report must name rank 1, and the cataloged metrics must
    carry the trip (satellite: no print-only watchdog)."""
    wd = TrainStepWatchdog(timeout_s=0.1, interval_s=0.02,
                           store=FileKVStore(str(tmp_path)), rank=0,
                           world_size=2, on_timeout=lambda w: None)
    try:
        wd.step_begin(step=3)
        deadline = time.time() + 5
        while time.time() < deadline and not wd.tripped:
            time.sleep(0.02)
        assert wd.tripped
        assert wd.stragglers == [1]
        err = wd.hang_error()
        assert "straggler" in str(err) and err.stragglers == [1]
        assert obs.counter("train.hang_aborts").value == 1
        assert obs.gauge("train.straggler_ranks").value == 1
    finally:
        wd.stop()


def test_watchdog_abort_token_consumed_exactly_once():
    """Hang translation keys on the abort TOKEN, not trip state: no
    token ⇒ a KeyboardInterrupt is a genuine ctrl-C and propagates;
    a sent token translates exactly once even after a re-arm cleared
    the trip flags."""
    wd = TrainStepWatchdog(timeout_s=9.0, interval_s=0.05,
                           on_timeout=lambda w: None)
    assert wd.consume_abort() is None
    wd._abort_error = wd.hang_error()
    wd._abort_sent_at = time.monotonic()
    wd.step_begin(1)          # re-arm clears tripped, NOT the token
    wd.step_end()
    err = wd.consume_abort()
    assert isinstance(err, TrainHangError)
    assert wd.consume_abort() is None
    wd.stop()


def test_watchdog_monitor_hibernates_when_idle_and_rearms():
    """A finished run must not leak a polling thread — the monitor
    hibernates after the idle budget and a later arm restarts it."""
    wd = TrainStepWatchdog(timeout_s=5.0, interval_s=0.01,
                           on_timeout=lambda w: None)
    try:
        wd.step_begin(0)
        wd.step_end()
        deadline = time.time() + 5
        while time.time() < deadline and wd._thread is not None:
            time.sleep(0.02)
        assert wd._thread is None
        wd.step_begin(1)
        assert wd._thread is not None
        wd.step_end()
    finally:
        wd.stop()


def test_watchdog_rearm_after_trip_is_monitored():
    """A supervised restart re-arms right after an abort; the new arm
    must get a live monitor (the dying thread's slot is released
    before the abort fires), proven by a second trip."""
    wd = TrainStepWatchdog(timeout_s=0.05, interval_s=0.01,
                           on_timeout=lambda w: None)
    try:
        wd.step_begin(0)
        deadline = time.time() + 5
        while time.time() < deadline and not wd.tripped:
            time.sleep(0.01)
        assert wd.tripped
        wd.step_end()
        wd.step_begin(1)             # clears tripped, spawns monitor
        deadline = time.time() + 5
        while time.time() < deadline and not wd.tripped:
            time.sleep(0.01)
        assert wd.tripped            # the new arm IS monitored
        wd.step_end()
    finally:
        wd.stop()


def test_watchdog_default_abort_refused_off_main_thread():
    """CPython delivers KeyboardInterrupt only in the main thread: the
    default abort armed from a worker thread could neither stop the
    hung step nor spare unrelated main-thread work — refused up front
    unless an on_timeout abort channel is supplied."""
    import threading as _th

    wd = TrainStepWatchdog(timeout_s=9.0, interval_s=0.5)
    errs = []

    def worker():
        try:
            wd.step_begin(0)
        except RuntimeError as e:
            errs.append(e)

    t = _th.Thread(target=worker)
    t.start()
    t.join()
    assert errs and "on_timeout" in str(errs[0])
    wd.stop()

    # with an abort channel, worker-thread arming is fine
    wd2 = TrainStepWatchdog(timeout_s=9.0, interval_s=0.5,
                            on_timeout=lambda w: None)
    ok = []
    t2 = _th.Thread(target=lambda: ok.append(wd2.step_begin(1)))
    t2.start()
    t2.join()
    assert ok
    wd2.step_end()
    wd2.stop()


def test_dataloader_seed_refused_with_external_sampler():
    """seed= only governs the loader-built sampler; pairing it with an
    external batch_sampler would record a seed the ordering never used
    and let a resume silently fast-forward the wrong permutation."""
    from paddle_tpu.io import BatchSampler
    ds = _RecData()
    with pytest.raises(ValueError, match="external"):
        pio.DataLoader(ds, batch_sampler=BatchSampler(
            ds, shuffle=True, batch_size=4), seed=7)


def test_hang_report_flags_wedged_collective(tmp_path):
    """When every rank's heartbeat predates the armed step and none
    lags the rest, the whole job blocked at one step — the report must
    suspect a wedged collective, not blame the local pipeline."""
    import json as _json

    store = FileKVStore(str(tmp_path))
    old = time.time() - 5.0
    store.put("watchdog/default/1", _json.dumps({"ts": old, "ops": 7}))
    wd = TrainStepWatchdog(timeout_s=0.3, interval_s=0.05, store=store,
                           rank=0, world_size=2,
                           on_timeout=lambda w: None)
    try:
        wd.step_begin(0)
        time.sleep(0.1)        # let the arm-time publish land...
        store.put("watchdog/default/0",
                  _json.dumps({"ts": old, "ops": 7}))  # ...then stall
        deadline = time.time() + 5
        while time.time() < deadline and not wd.tripped:
            time.sleep(0.02)
        assert wd.tripped
        assert wd.stragglers == [] and wd.collective_suspect
        assert "wedged collective" in str(wd.hang_error())
    finally:
        wd.stop()


# ------------------------------------------- preemption-safe checkpointing
class _Sigterm(Callback):
    """Delivers a REAL SIGTERM to this process mid-training."""

    def __init__(self, at_step):
        self.at_step = at_step

    def on_train_batch_end(self, step, logs=None):
        if step == self.at_step:
            os.kill(os.getpid(), signal.SIGTERM)


def test_sigterm_flushes_committed_checkpoint_and_stops(tmp_path):
    root = str(tmp_path / "ck")
    model, loader = _build()
    cb = FaultTolerantCheckpoint(root, every_n_steps=0,
                                 dataloader=loader)
    hist = model.fit(loader, epochs=1, verbose=0,
                     callbacks=[_Sigterm(2), cb])
    # stopped at the step boundary, not at epoch end; the flush is
    # COMMITTED (loadable), capturing the step we stopped at
    assert cb.preempted and len(hist["loss"]) == 3
    latest = dc.latest_committed(root)
    assert latest is not None and latest.endswith("step_00000003")
    assert obs.counter("train.preemptions").value == 1
    # the handler was restored: SIGTERM dispositions don't leak
    assert signal.getsignal(signal.SIGTERM) is not \
        cb._handler._on_signal


def test_preempted_callback_is_reusable_for_the_resume_fit(tmp_path):
    """The natural resume-retry pattern — call fit again with the SAME
    callback instance — must work: a consumed preemption notice
    (stopped/preempted/handler.triggered) is reset per fit, and the
    second fit resumes from the flush and runs to completion."""
    root = str(tmp_path / "ck")
    model, loader = _build()
    cb = FaultTolerantCheckpoint(root, every_n_steps=0,
                                 dataloader=loader)
    h1 = model.fit(loader, epochs=1, verbose=0,
                   callbacks=[_Sigterm(2), cb])
    assert cb.preempted and len(h1["loss"]) == 3

    model2, loader2 = _build()
    cb.dataloader = loader2
    h2 = model2.fit(loader2, epochs=1, verbose=0, callbacks=[cb])
    # resumed at step 3 and finished the epoch — NOT stopped after one
    # batch by the stale notice
    assert not cb.preempted
    assert len(h2["loss"]) == 5
    assert cb.global_step == 8


def test_preemption_handler_restores_disposition():
    old = signal.getsignal(signal.SIGTERM)
    with PreemptionHandler() as h:
        assert h.installed
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(0.01)
        assert h.triggered
    assert signal.getsignal(signal.SIGTERM) is old


@pytest.mark.chaos
def test_chaos_preempt_site_drives_the_flush_path(tmp_path):
    """train.preempt chaos: an injected error at the step boundary is
    a delivered preemption notice — same flush-and-stop path, no real
    signal needed."""
    _arm()
    _chaos.install("train.preempt", kind="error", times=1,
                   match=lambda c: c.get("step") == 2)
    root = str(tmp_path / "ck")
    model, loader = _build()
    cb = FaultTolerantCheckpoint(root, every_n_steps=0,
                                 dataloader=loader)
    hist = model.fit(loader, epochs=1, verbose=0, callbacks=[cb])
    assert cb.preempted and len(hist["loss"]) == 2
    assert dc.latest_committed(root).endswith("step_00000002")


@pytest.mark.chaos
def test_checkpoint_save_chaos_leaves_dir_uncommitted(tmp_path):
    """A writer killed mid-save (train.checkpoint_save fires after the
    stale marker drop) must leave an UNCOMMITTED dir that resume
    skips — the commit protocol's whole point."""
    model, loader = _build()
    root = str(tmp_path)
    save_train_checkpoint(root, 1, model.network, model._optimizer,
                          loader)
    _arm()
    _chaos.install("train.checkpoint_save", kind="error", times=1)
    with pytest.raises(_chaos.ChaosError):
        save_train_checkpoint(root, 2, model.network,
                              model._optimizer, loader)
    latest = dc.latest_committed(root)
    assert latest is not None and latest.endswith("step_00000001")
    assert not dc.is_committed(os.path.join(root, "step_00000002"))
    assert obs.counter("train.checkpoint_saves").value == 1


# --------------------------------------------------- dataloader position
def test_dataloader_state_roundtrip_replays_exact_order():
    _SERVED.clear()
    full = pio.DataLoader(_RecData(), batch_size=4, shuffle=True,
                          seed=11)
    list(full)
    oracle = list(_SERVED)

    _SERVED.clear()
    first = pio.DataLoader(_RecData(), batch_size=4, shuffle=True,
                           seed=11)
    it = iter(first)
    for _ in range(3):
        next(it)
    state = first.state_dict()
    assert state["batches_served"] == 3 and state["seed"] == 11

    resumed = pio.DataLoader(_RecData(), batch_size=4, shuffle=True,
                             seed=11)
    resumed.set_state_dict(state)
    list(resumed)
    # fast-forward: the skipped batches were NOT re-loaded, and the
    # consumed order equals the uninterrupted pass exactly
    assert _SERVED == oracle
    # next epoch reshuffles (position rolled over)
    assert resumed.state_dict() == {"epoch": 1, "batches_served": 0,
                                    "seed": 11}


def test_dataloader_resume_refuses_seed_mismatch():
    dl = pio.DataLoader(_RecData(), batch_size=4, shuffle=True, seed=1)
    with pytest.raises(ValueError, match="seed"):
        dl.set_state_dict({"epoch": 0, "batches_served": 2, "seed": 9})
    # BOTH directions: an unseeded checkpoint into a seeded loader
    # would fast-forward an unrelated permutation — refuse, don't
    # silently corrupt the data order
    with pytest.raises(ValueError, match="seed"):
        dl.set_state_dict({"epoch": 0, "batches_served": 2,
                           "seed": None})


def test_prefetch_worker_exits_when_iterator_abandoned():
    """Mid-epoch abandonment (preemption / crash under run_resilient)
    must unwind the background prefetch worker — a thread blocked on
    a full queue forever would leak once per crashed attempt."""
    import threading as _th

    before = set(_th.enumerate())
    dl = pio.DataLoader(_RecData(), batch_size=2, num_workers=1,
                        prefetch_factor=1)
    for _ in range(2):                    # repeated abandonment
        it = iter(dl)
        next(it)
        it.close()
    deadline = time.time() + 5
    while time.time() < deadline and (set(_th.enumerate()) - before):
        time.sleep(0.05)
    leaked = set(_th.enumerate()) - before
    assert not leaked, leaked


# --------------------------------------------- crash-resume (acceptance)
@pytest.mark.chaos
def test_resume_equivalence_bitwise(tmp_path):
    """THE acceptance drill: train 8 steps uninterrupted vs train 4 +
    chaos-kill + run_resilient resume 4 — bitwise-identical parameters
    AND identical consumed data order."""
    _SERVED.clear()
    model, loader = _build()
    model.fit(loader, epochs=1, verbose=0)
    oracle = _params(model)
    oracle_order = list(_SERVED)
    assert len(oracle_order) == 32                 # 8 batches of 4

    _SERVED.clear()
    _arm()
    _chaos.install("train.step", kind="error", times=1,
                   match=lambda c: c.get("step") == 4)
    root = str(tmp_path / "ck")
    out = {}
    restarts = []

    def worker(attempt):
        m, dl = _build()
        cb = FaultTolerantCheckpoint(root, every_n_steps=1,
                                     dataloader=dl)
        m.fit(dl, epochs=1, verbose=0, callbacks=[cb])
        out["model"], out["cb"] = m, cb

    run_resilient(worker, max_restarts=2, backoff_s=0.01,
                  on_restart=lambda a, e: restarts.append((a, e)))
    assert len(restarts) == 1
    assert isinstance(restarts[0][1], _chaos.ChaosError)
    assert out["cb"].resumed_from.endswith("step_00000004")
    assert obs.counter("train.restarts").value == 1

    resumed = _params(out["model"])
    for k in oracle:
        assert oracle[k].tobytes() == resumed[k].tobytes(), k
    # data order: attempt 1 consumed batches 0..4 (batch 4's step
    # crashed), the resume fast-forwarded WITHOUT reloading 0..3 and
    # replayed exactly batches 4..7
    assert _SERVED == oracle_order[:20] + oracle_order[16:]


@pytest.mark.chaos
def test_resume_equivalence_across_epochs(tmp_path):
    """Multi-epoch resume: a crash in epoch 1 of 2 must NOT re-run
    epoch 0 — the fit epoch budget carries across the restart (via the
    checkpointed fit epoch) and the resumed run still matches the
    uninterrupted 2-epoch run bitwise."""
    _SERVED.clear()
    model, loader = _build()
    model.fit(loader, epochs=2, verbose=0)
    oracle = _params(model)
    oracle_order = list(_SERVED)
    assert len(oracle_order) == 64

    _SERVED.clear()
    _arm()
    _chaos.install("train.step", kind="error", times=1,
                   match=lambda c: c.get("step") == 10)  # epoch 1, #2
    root = str(tmp_path / "ck")
    out = {}

    def worker(attempt):
        m, dl = _build()
        cb = FaultTolerantCheckpoint(root, every_n_steps=1,
                                     dataloader=dl)
        m.fit(dl, epochs=2, verbose=0, callbacks=[cb])
        out["m"] = m

    run_resilient(worker, max_restarts=2, backoff_s=0.01)
    resumed = _params(out["m"])
    for k in oracle:
        assert oracle[k].tobytes() == resumed[k].tobytes(), k
    # attempt 1 consumed epoch 0 + epoch-1 batches 0..2 (the crashed
    # fetch); the resume replayed ONLY epoch-1 batches 2..7 — epoch 0
    # was not re-trained
    assert _SERVED == oracle_order[:44] + oracle_order[40:]


@pytest.mark.chaos
def test_resume_at_epoch_boundary_does_not_replay_epoch_end(tmp_path):
    """A checkpoint flushed at an epoch's final batch must resume at
    the NEXT epoch's start: re-entering the finished epoch would fire
    on_epoch_end (and eval) a second time — double-stepping epoch-wise
    LR schedulers and double-counting early-stop patience."""
    epoch_ends = []

    class _Track(Callback):
        def on_epoch_end(self, epoch, logs=None):
            epoch_ends.append(epoch)

    _SERVED.clear()
    model, loader = _build()
    model.fit(loader, epochs=2, verbose=0)
    oracle = _params(model)

    _arm()
    # crash on epoch 1's FIRST step: the latest committed flush is the
    # epoch-0-final-batch checkpoint
    _chaos.install("train.step", kind="error", times=1,
                   match=lambda c: c.get("step") == 8)
    root = str(tmp_path / "ck")
    out = {}

    def worker(attempt):
        m, dl = _build()
        cb = FaultTolerantCheckpoint(root, every_n_steps=1,
                                     dataloader=dl)
        m.fit(dl, epochs=2, verbose=0, callbacks=[_Track(), cb])
        out["m"] = m

    run_resilient(worker, max_restarts=2, backoff_s=0.01)
    resumed = _params(out["m"])
    for k in oracle:
        assert oracle[k].tobytes() == resumed[k].tobytes(), k
    # attempt 1 ended epoch 0 once; the resume ran ONLY epoch 1 —
    # epoch 0's end-of-epoch hooks never replayed
    assert epoch_ends == [0, 1]


@pytest.mark.chaos
def test_crashed_fit_still_restores_sigterm_handler(tmp_path):
    """on_train_end runs even when an attempt crashes mid-loop: the
    crashed attempt's SIGTERM handler must not stay installed (a stale
    handler on a dead callback would swallow the NEXT attempt's
    preemption notice)."""
    old = signal.getsignal(signal.SIGTERM)
    _arm()
    _chaos.install("train.step", kind="error", times=1)
    model, loader = _build()
    cb = FaultTolerantCheckpoint(str(tmp_path / "ck"), every_n_steps=1,
                                 dataloader=loader)
    with pytest.raises(_chaos.ChaosError):
        model.fit(loader, epochs=1, verbose=0, callbacks=[cb])
    assert signal.getsignal(signal.SIGTERM) == old


def test_watchdog_trip_state_clears_on_rearm():
    """A stale tripped flag would rebrand a later genuine ctrl-C as a
    TrainHangError — re-arming must clear the previous trip."""
    wd = TrainStepWatchdog(timeout_s=0.05, interval_s=0.01,
                           on_timeout=lambda w: None)
    try:
        wd.step_begin(0)
        deadline = time.time() + 5
        while time.time() < deadline and not wd.tripped:
            time.sleep(0.01)
        assert wd.tripped
        wd.step_begin(1)
        assert not wd.tripped and wd.stragglers is None
        wd.step_end()
    finally:
        wd.stop()


def test_resume_restores_lazy_optimizer_accumulators(tmp_path):
    """Adam moments et al. are created lazily on the first step(); a
    FRESH optimizer's resume must still restore them (the load forces
    accumulator creation before building the template) — without this,
    a stateful-optimizer resume silently drops its moments."""
    paddle.seed(1)
    net = nn.Linear(4, 2)
    opt = paddle.optimizer.AdamW(1e-2, parameters=net.parameters())
    x = paddle.to_tensor(np.random.randn(4, 4).astype("f4"))
    for _ in range(2):
        net(x).mean().backward()
        opt.step()
        opt.clear_grad()
    save_train_checkpoint(str(tmp_path), 2, net, opt)

    paddle.seed(1)
    net2 = nn.Linear(4, 2)
    opt2 = paddle.optimizer.AdamW(1e-2, parameters=net2.parameters())
    assert not opt2._accumulators       # fresh: nothing created yet
    meta = load_train_checkpoint(str(tmp_path), net2, opt2)
    assert meta["step"] == 2
    sd1, sd2 = opt.state_dict(), opt2.state_dict()
    assert set(sd1) == set(sd2)
    for k, v in sd1.items():
        if hasattr(v, "numpy"):
            np.testing.assert_array_equal(v.numpy(), sd2[k].numpy(), k)


def test_resume_restores_lr_scheduler_and_global_step(tmp_path):
    """Optimizer PYTHON state — the LR schedule position and
    global_step — must survive resume too: tensors restore in place,
    but these only round-trip if the load hands them back via
    set_state_dict (a scheduled-LR resume that silently restarts its
    schedule trains at the wrong LR)."""
    paddle.seed(2)
    net = nn.Linear(4, 2)
    sched = paddle.optimizer.lr.StepDecay(0.1, step_size=2)
    opt = paddle.optimizer.SGD(sched, parameters=net.parameters())
    x = paddle.to_tensor(np.random.randn(4, 4).astype("f4"))
    for _ in range(6):
        net(x).mean().backward()
        opt.step()
        opt.clear_grad()
        sched.step()
    want_lr, want_gs = sched(), opt._global_step
    assert want_lr < 0.1                    # schedule actually moved
    save_train_checkpoint(str(tmp_path), 6, net, opt)

    paddle.seed(2)
    net2 = nn.Linear(4, 2)
    sched2 = paddle.optimizer.lr.StepDecay(0.1, step_size=2)
    opt2 = paddle.optimizer.SGD(sched2, parameters=net2.parameters())
    load_train_checkpoint(str(tmp_path), net2, opt2)
    assert sched2() == want_lr
    assert sched2.last_epoch == sched.last_epoch
    assert opt2._global_step == want_gs


def test_run_resilient_bounded_retries_with_backoff():
    calls = []

    def always_fails(attempt):
        calls.append(attempt)
        raise RuntimeError("boom")

    t0 = time.perf_counter()
    with pytest.raises(RuntimeError, match="boom"):
        run_resilient(always_fails, max_restarts=2, backoff_s=0.02,
                      backoff_factor=2.0)
    # attempts 0,1,2 ran; backoff 0.02 + 0.04 elapsed between them
    assert calls == [0, 1, 2]
    assert time.perf_counter() - t0 >= 0.06
    assert obs.counter("train.restarts").value == 2

    # KeyboardInterrupt always propagates without a restart
    def ctrl_c(attempt):
        calls.append("kbd")
        raise KeyboardInterrupt()

    with pytest.raises(KeyboardInterrupt):
        run_resilient(ctrl_c, max_restarts=5, backoff_s=0)
    assert calls.count("kbd") == 1


# --------------------------------------------------------- chaos parsing
@pytest.mark.chaos
def test_chaos_env_spec_training_sites_roundtrip():
    """Env-spec round-trip for the ISSUE 15 hook sites: dotted train.*
    site names parse, budgets and slow-seconds apply, and the clause
    list maps 1:1 onto installed rules."""
    spec = ("train.step:error:2;train.data_fetch:slow:0.05;"
            "train.checkpoint_save:alloc:1;train.preempt:error:1")
    os.environ[_chaos.ENV] = spec
    with pytest.raises(_chaos.ChaosError):
        _chaos.hit("train.step")
    rules = [r for r in _chaos._rules if r.from_env]
    assert sorted(r.site for r in rules) == [
        "train.checkpoint_save", "train.data_fetch", "train.preempt",
        "train.step"]
    kinds = {r.site: r.kind for r in rules}
    assert kinds["train.data_fetch"] == "slow"
    assert kinds["train.checkpoint_save"] == "alloc"
    with pytest.raises(_chaos.ChaosError):
        _chaos.hit("train.step")
    _chaos.hit("train.step")                       # budget of 2 spent
    t0 = time.perf_counter()
    _chaos.hit("train.data_fetch")                 # slow, not an error
    assert time.perf_counter() - t0 >= 0.04
    with pytest.raises(_chaos.ChaosAllocError):
        _chaos.hit("train.checkpoint_save")
    _chaos.hit("train.checkpoint_save")            # budget of 1 spent
    with pytest.raises(_chaos.ChaosError):
        _chaos.hit("train.preempt")
