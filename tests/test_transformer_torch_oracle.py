"""Transformer layer numerics vs torch with copied weights (reference
mechanism: test/legacy_test/test_transformer_api.py numeric checks)."""
import numpy as np
import torch

import paddle_tpu as paddle
from paddle_tpu import nn

rs = np.random.RandomState(11)
E, NH, B, S = 16, 4, 2, 6


def _set(lin, w, b):
    lin.weight._assign_array(paddle.to_tensor(w)._data)
    lin.bias._assign_array(paddle.to_tensor(b)._data)


def test_multi_head_attention_matches_torch():
    ours = nn.MultiHeadAttention(E, NH)
    theirs = torch.nn.MultiheadAttention(E, NH, batch_first=True)
    # torch packs qkv into in_proj [3E, E] (out = x @ W^T + b)
    wq = rs.randn(E, E).astype(np.float32)
    wk = rs.randn(E, E).astype(np.float32)
    wv = rs.randn(E, E).astype(np.float32)
    wo = rs.randn(E, E).astype(np.float32)
    bq, bk, bv, bo = (rs.randn(E).astype(np.float32) for _ in range(4))
    with torch.no_grad():
        theirs.in_proj_weight.copy_(torch.tensor(
            np.concatenate([wq, wk, wv], 0)))
        theirs.in_proj_bias.copy_(torch.tensor(
            np.concatenate([bq, bk, bv])))
        theirs.out_proj.weight.copy_(torch.tensor(wo))
        theirs.out_proj.bias.copy_(torch.tensor(bo))
    # ours uses out = x @ W + b -> transpose torch's W
    _set(ours.q_proj, wq.T, bq)
    _set(ours.k_proj, wk.T, bk)
    _set(ours.v_proj, wv.T, bv)
    _set(ours.out_proj, wo.T, bo)

    x = rs.randn(B, S, E).astype(np.float32)
    out = ours(paddle.to_tensor(x))
    ref, _ = theirs(torch.tensor(x), torch.tensor(x), torch.tensor(x))
    np.testing.assert_allclose(out.numpy(), ref.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_transformer_encoder_layer_matches_torch():
    ours = nn.TransformerEncoderLayer(E, NH, dim_feedforward=32,
                                      dropout=0.0, activation="relu",
                                      normalize_before=False)
    theirs = torch.nn.TransformerEncoderLayer(
        E, NH, dim_feedforward=32, dropout=0.0, activation="relu",
        batch_first=True, norm_first=False)

    wq = rs.randn(E, E).astype(np.float32)
    wk = rs.randn(E, E).astype(np.float32)
    wv = rs.randn(E, E).astype(np.float32)
    wo = rs.randn(E, E).astype(np.float32)
    bq, bk, bv, bo = (rs.randn(E).astype(np.float32) for _ in range(4))
    w1 = rs.randn(32, E).astype(np.float32)
    b1 = rs.randn(32).astype(np.float32)
    w2 = rs.randn(E, 32).astype(np.float32)
    b2 = rs.randn(E).astype(np.float32)
    with torch.no_grad():
        theirs.self_attn.in_proj_weight.copy_(torch.tensor(
            np.concatenate([wq, wk, wv], 0)))
        theirs.self_attn.in_proj_bias.copy_(torch.tensor(
            np.concatenate([bq, bk, bv])))
        theirs.self_attn.out_proj.weight.copy_(torch.tensor(wo))
        theirs.self_attn.out_proj.bias.copy_(torch.tensor(bo))
        theirs.linear1.weight.copy_(torch.tensor(w1))
        theirs.linear1.bias.copy_(torch.tensor(b1))
        theirs.linear2.weight.copy_(torch.tensor(w2))
        theirs.linear2.bias.copy_(torch.tensor(b2))

    attn = ours.self_attn
    _set(attn.q_proj, wq.T, bq)
    _set(attn.k_proj, wk.T, bk)
    _set(attn.v_proj, wv.T, bv)
    _set(attn.out_proj, wo.T, bo)
    _set(ours.linear1, w1.T, b1)
    _set(ours.linear2, w2.T, b2)

    x = rs.randn(B, S, E).astype(np.float32)
    ours.eval()
    theirs.eval()
    out = ours(paddle.to_tensor(x))
    ref = theirs(torch.tensor(x))
    np.testing.assert_allclose(out.numpy(), ref.detach().numpy(),
                               rtol=1e-4, atol=1e-4)
