"""Smoke-run the five BASELINE.json workload configs (reference
capability matrix: ResNet/CIFAR dygraph, BERT MLM AMP-O2, GPT
DP+sharding-1, Llama TP4xPP2, MoE expert-parallel) on the 8-device CPU
mesh."""
import importlib.util
import os
import sys

import pytest

_DIR = os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                    "workloads")


def _load(name):
    spec = importlib.util.spec_from_file_location(
        f"workload_{name}", os.path.join(_DIR, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_resnet_cifar10_dygraph():
    losses = _load("resnet50_cifar10").main(smoke=True, steps=6)
    assert len(losses) == 6


def test_bert_mlm_amp_o2():
    losses = _load("bert_mlm_amp").main(smoke=True, steps=6)
    assert losses[-1] < losses[0]


def test_gpt_dp_sharding1():
    losses = _load("gpt_dp_sharding1").main(smoke=True, steps=4)
    assert losses[-1] < losses[0]


def test_llama_tp_pp():
    losses = _load("llama_tp_pp_sharding3").main(smoke=True, steps=3)
    assert losses[-1] < losses[0]


def test_moe_expert_parallel():
    losses = _load("moe_ep").main(smoke=True, steps=4)
    assert losses[-1] < losses[0]
